"""The epoch loop as `lax.scan` + the reference-compatible driver API.

The reference's `run_simulation` (reference simulation_utils.py:26-112) is a
Python `for` over epochs carrying `(B_state, W_prev, server_consensus_weight)`
with per-epoch `.item()` host transfers. Here the whole loop — variant
dispatch, bond-reset injection, the kernel, and the dividend-per-1000-tao
conversion (simulation_utils.py:45-49, 95-107) — is one jitted
`lax.scan`: carry = `(B, W_prev, C_prev)`, xs = the scenario's stacked
`(W[E,V,M], S[E,V], epoch_index)`. A single device round-trip returns every
per-epoch output at once.

`simulate_constant` is the throughput path: weights constant across epochs
are closed over (no `[E, V, M]` HBM blow-up at 10k+ epochs) and total
dividends accumulate inside the carry.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.epoch import (
    _EMA_MODES,
    BondsMode,
    capacity_bonds_update,
    ema_bonds_update,
    relative_bonds_update,
    yuma_epoch,
)
from yuma_simulation_tpu.ops.liquid import liquid_alpha_rate
from yuma_simulation_tpu.models.variants import (
    ResetMode,
    VariantSpec,
    variant_for_version,
)
from yuma_simulation_tpu.ops.normalize import normalize_weight_rows
from yuma_simulation_tpu.scenarios.base import Scenario


@dataclass
class SimulationResult:
    """Host-side view of one simulated scenario."""

    dividends: np.ndarray  # [E, V] dividend per 1000 tao per epoch
    bonds: Optional[np.ndarray]  # [E, V, M] post-epoch bond state
    incentives: Optional[np.ndarray]  # [E, M] server incentive
    consensus: Optional[np.ndarray]  # [E, M] quantized consensus


def _miner_shardings(mesh: Mesh):
    """`([V, M], [M])` NamedShardings with the miner axis over the mesh's
    last axis (the ``model`` axis of :func:`..parallel.mesh.make_mesh`).

    The miner axis is this framework's sequence-parallel analogue
    (SURVEY.md §5): the bisection/sort consensus is per-miner and stays
    shard-local; only the row-normalization sums, consensus-sum divide,
    liquid-alpha quantile sort and dividend reductions cross shards.
    """
    axis = mesh.axis_names[-1]
    vm = NamedSharding(mesh, PartitionSpec(None, axis))
    m = NamedSharding(mesh, PartitionSpec(axis))
    return vm, m


def _dividends_per_1k(D_n, S, config, dtype):
    """Dividend per 1000 tao (reference simulation_utils.py:45-49,
    95-107), from NORMALIZED dividends and the *raw* stakes. One shared
    definition: this arithmetic is parity-critical and every engine path
    (XLA scan, fused case scan, scaled/constant throughput paths) must
    apply bit-identical ops."""
    stakes_units = jnp.asarray(S, dtype) * config.total_subnet_stake / 1000.0
    emission = (
        config.validator_emission_ratio * D_n * config.total_epoch_emission
    )
    return jnp.where(stakes_units > 1e-6, emission / stakes_units, 0.0)


def fused_hparams(config: YumaConfig) -> dict:
    """The one config -> fused-kernel hyperparameter mapping. This
    spelling is parity-critical (a drifted field silently changes the
    simulated model), so every fused call site — the engine paths here
    and bench.py's true-weights runner — must build its kwargs through
    this helper."""
    return dict(
        kappa=config.kappa,
        bond_penalty=config.bond_penalty,
        bond_alpha=config.bond_alpha,
        capacity_alpha=config.capacity_alpha,
        decay_rate=config.decay_rate,
        liquid_alpha=config.liquid_alpha,
        alpha_low=config.alpha_low,
        alpha_high=config.alpha_high,
        override_consensus_high=config.override_consensus_high,
        override_consensus_low=config.override_consensus_low,
        precision=config.consensus_precision,
    )


def config_is_batched(config) -> bool:
    """Whether any float leaf of the config pytree carries a leading
    batch axis (a config_grid grid). One shared predicate — the engines
    must agree on what counts as batched."""
    return any(jnp.ndim(leaf) > 0 for leaf in jax.tree.leaves(config))


def config_vmap_axes(config):
    """Per-leaf vmap in_axes for a possibly partially-batched config:
    batched leaves map over axis 0, scalar leaves broadcast. (The fused
    kernels broadcast scalars the same way via _pack_hp, so both engines
    accept mixed configs.)"""
    return jax.tree.map(lambda l: 0 if jnp.ndim(l) else None, config)


def _apply_reset(B, C_prev, epoch, reset_index, reset_epoch, reset_mode, M):
    """Zero the reset miner's bond column when the variant's rule fires
    (reference simulation_utils.py:62-88). `reset_epoch < 0` disables.

    The reference can only reset from epoch 1 onward (`B_state`/
    `server_consensus_weight` are still None at epoch 0), hence the
    `epoch > 0` gate.
    """
    do = (epoch == reset_epoch) & (epoch > 0) & (reset_index >= 0)
    if reset_mode is ResetMode.CONDITIONAL:
        prev_c = jnp.take(C_prev, jnp.clip(reset_index, 0, M - 1))
        do = do & (prev_c == 0.0)
    col = (jnp.arange(M) == reset_index) & do
    return jnp.where(col[None, :], jnp.zeros_like(B), B)


@partial(
    jax.jit,
    static_argnames=(
        "spec",
        "save_bonds",
        "save_incentives",
        "save_consensus",
        "consensus_impl",
        "mesh",
    ),
)
def _simulate_scan(
    weights: jnp.ndarray,  # [E, V, M]
    stakes: jnp.ndarray,  # [E, V]
    reset_index: jnp.ndarray,  # int32 scalar, -1 = none
    reset_epoch: jnp.ndarray,  # int32 scalar, -1 = none
    config: YumaConfig,
    spec: VariantSpec,
    save_bonds: bool = True,
    save_incentives: bool = True,
    save_consensus: bool = False,
    consensus_impl: str = "bisect",
    miner_mask: Optional[jnp.ndarray] = None,  # [M] 1=real, 0=padding
    mesh: Optional[Mesh] = None,  # shard the miner axis over mesh's last axis
):
    E, V, M = weights.shape
    dtype = weights.dtype
    shardings = None if mesh is None else _miner_shardings(mesh)

    def step(carry, xs):
        B, W_prev, C_prev = carry
        W, S, epoch = xs
        first = epoch == 0
        if shardings is not None:
            # Re-pin the layouts every epoch so GSPMD keeps the miner axis
            # sharded through the whole scan instead of gathering the carry.
            vm, m = shardings
            W = lax.with_sharding_constraint(W, vm)
            B = lax.with_sharding_constraint(B, vm)
            W_prev = lax.with_sharding_constraint(W_prev, vm)
            C_prev = lax.with_sharding_constraint(C_prev, m)

        if spec.reset_mode is not ResetMode.NONE:
            B = _apply_reset(
                B, C_prev, epoch, reset_index, reset_epoch, spec.reset_mode, M
            )

        kernel_prev = None
        if spec.bonds_mode is BondsMode.EMA_PREV:
            # Epoch 0 falls back to this epoch's normalized weights
            # (reference yumas.py:299-300).
            kernel_prev = jnp.where(
                first, normalize_weight_rows(W.astype(dtype)), W_prev
            )

        res = yuma_epoch(
            W,
            S,
            B,
            config,
            bonds_mode=spec.bonds_mode,
            W_prev=kernel_prev,
            first_epoch=first,
            consensus_impl=consensus_impl,
            miner_mask=miner_mask,
        )

        B_next = res[spec.bond_state_key]
        W_prev_next = res["weight"] if spec.carries_prev_weights else W_prev
        C_next = res["server_consensus_weight"]
        if shardings is not None:
            vm, m = shardings
            B_next = lax.with_sharding_constraint(B_next, vm)
            W_prev_next = lax.with_sharding_constraint(W_prev_next, vm)
            C_next = lax.with_sharding_constraint(C_next, m)

        # Note the conversion uses the *raw* case stakes, not the
        # normalized kernel stakes.
        dividends = _dividends_per_1k(
            res["validator_reward_normalized"], S, config, dtype
        )

        ys = {"dividends": dividends}
        if save_bonds:
            ys["bonds"] = B_next
        if save_incentives:
            ys["incentives"] = res["server_incentive"]
        if save_consensus:
            ys["consensus"] = C_next
        return (B_next, W_prev_next, C_next), ys

    carry0 = (
        jnp.zeros((V, M), dtype),
        jnp.zeros((V, M), dtype),
        jnp.zeros((M,), dtype),
    )
    xs = (weights, stakes, jnp.arange(E, dtype=jnp.int32))
    _, ys = lax.scan(step, carry0, xs)
    return ys


@partial(
    jax.jit,
    static_argnames=(
        "spec",
        "save_bonds",
        "save_incentives",
        "save_consensus",
        "mxu",
    ),
)
def _simulate_case_fused(
    weights: jnp.ndarray,  # [E, V, M] or batched [B, E, V, M]
    stakes: jnp.ndarray,  # [E, V] or [B, E, V]
    reset_index: jnp.ndarray,  # scalar, or [B] when batched
    reset_epoch: jnp.ndarray,
    config: YumaConfig,
    spec: VariantSpec,
    save_bonds: bool = True,
    save_incentives: bool = True,
    save_consensus: bool = False,
    mxu: bool = False,
):
    """The fused-Pallas twin of :func:`_simulate_scan`: the whole epoch
    loop — per-epoch weights/stakes streamed from HBM, reset injection,
    liquid alpha — runs as ONE Pallas program
    (:func:`yuma_simulation_tpu.ops.pallas_epoch.fused_case_scan`); only
    the dividend-per-1000-tao conversion (linear, needs the raw per-epoch
    stakes) happens out here. Returns the same ys dict as
    `_simulate_scan`."""
    from yuma_simulation_tpu.ops.pallas_epoch import fused_case_scan

    dtype = weights.dtype
    res = fused_case_scan(
        weights,
        stakes,
        reset_index=reset_index,
        reset_epoch=reset_epoch,
        reset_mode=spec.reset_mode,
        mode=spec.bonds_mode,
        mxu=mxu,
        save_bonds=save_bonds,
        save_incentives=save_incentives,
        save_consensus=save_consensus,
        **fused_hparams(config),
    )
    if config_is_batched(config):
        # Batched [B] config leaves (a grid aligned with the scenario
        # axis): the kernel consumed them as per-scenario vectors; the
        # per-1000-tao conversion maps them the same way (scalar leaves
        # broadcast).
        dividends = jax.vmap(
            lambda d, s, c: _dividends_per_1k(d, s, c, dtype),
            in_axes=(0, 0, config_vmap_axes(config)),
        )(res["dividends_normalized"], stakes, config)
    else:
        dividends = _dividends_per_1k(
            res["dividends_normalized"], stakes, config, dtype
        )
    ys = {"dividends": dividends}
    for key in ("bonds", "incentives", "consensus"):
        if key in res:
            ys[key] = res[key]
    return ys


def simulate(
    scenario: Scenario,
    yuma_version: str,
    config: Optional[YumaConfig] = None,
    *,
    save_bonds: bool = True,
    save_incentives: bool = True,
    save_consensus: bool = False,
    consensus_impl: str = "bisect",
    epoch_impl: str = "auto",
    dtype=jnp.float32,
    mesh: Optional[Mesh] = None,
) -> SimulationResult:
    """Simulate one scenario under one named version; returns host arrays.

    Memory note: `save_bonds`/`save_incentives` default True to mirror
    the reference driver's outputs, which materializes `[E, V, M]`
    per-epoch bonds on device AND fetches them to host. Fine at the
    suite's E=40; at long epoch counts prefer `save_bonds=False` (or
    the `simulate_constant`/`simulate_scaled` throughput paths, which
    accumulate totals in-carry and keep HBM flat).

    `epoch_impl`:
      - "auto" (default): run the whole epoch loop as a single Pallas
        program (`fused_case_scan` — per-epoch weights/stakes streamed
        through VMEM, the flagship kernel) when the variant/config/shape
        allow it on a real TPU, else the XLA `lax.scan`. Prefers the
        MXU variant (exact limb-split support, bitwise the VPU scan,
        ~1.6x) wherever it covers V. The fused path matches the XLA
        path to reduction-order rounding (pinned against the golden CSV
        surface by tests/unit/test_fused_case_scan.py).
      - "xla": always the `lax.scan` over the unfused epoch kernel.
      - "fused_scan": require the fused path with VPU reductions (raises
        if ineligible; off-TPU it runs in interpret mode — correct but
        slow, for tests).
      - "fused_scan_mxu": the fused path with the consensus support on
        the MXU as the EXACT limb-split integer contraction (r4):
        bitwise-identical outputs to "fused_scan", ~1.6x faster, V <=
        2^14 — what "auto" selects on TPU (parity pinned on chip in
        MXU_PARITY.json via tools/tpu_parity.py).

    `consensus_impl`: "bisect" (default), "sorted" (bitwise twin — the
    fuzz battery pins them equal — but with pathological XLA compile
    times at >= 512x8192 cells), or "auto" (defer to the engine: the
    fused path when epoch_impl selects it, else the shape-gated
    sorted/bisect default).

    With ``mesh``, the miner axis of every `[V, M]` matrix is sharded over
    the mesh's last axis for the whole multi-epoch scan — the path for
    subnets whose `V x M` state outgrows one chip's HBM (XLA path only).
    Sharded results match the unsharded run to within one u16 consensus
    grid step — cross-shard psum ordering can flip the truncating
    quantizer by one 2^-17 step on knife-edge values — with bounds pinned
    by tests/unit/test_multichip.py.
    """
    config = config if config is not None else YumaConfig()
    spec = variant_for_version(yuma_version)
    weights = jnp.asarray(scenario.weights, dtype)
    stakes = jnp.asarray(scenario.stakes, dtype)
    reset_index = jnp.asarray(
        -1 if scenario.reset_bonds_index is None else scenario.reset_bonds_index,
        jnp.int32,
    )
    reset_epoch = jnp.asarray(
        -1 if scenario.reset_bonds_epoch is None else scenario.reset_bonds_epoch,
        jnp.int32,
    )
    # consensus_impl="auto" defers to the engine: the fused path (which
    # computes by bisection) when epoch_impl selects it, else the
    # shape-gated sorted/bisect default (the two are bitwise twins —
    # tests/unit/test_consensus_fuzz.py — so this is purely a
    # compile/runtime-cost choice, ops/consensus.py).
    if consensus_impl not in ("auto", "sorted", "bisect"):
        raise ValueError(
            f"unknown consensus_impl {consensus_impl!r}; "
            "expected 'auto', 'sorted' or 'bisect'"
        )
    consensus_auto = consensus_impl == "auto"

    if epoch_impl == "auto":
        from yuma_simulation_tpu.ops.pallas_epoch import (
            exact_mxu_support_covers,
            fused_case_scan_eligible,
        )

        if (
            mesh is None
            and (consensus_auto or consensus_impl == "bisect")
            and weights.shape[0] >= 1
            and fused_case_scan_eligible(
                weights.shape, spec.bonds_mode, config, dtype, save_bonds
            )
        ):
            # Since r4 the MXU scan's consensus support is EXACT (the
            # limb-split integer contraction, ~1.6x the VPU scan) and the
            # whole scan is bitwise the VPU scan, so auto prefers it
            # wherever the limb split covers V.
            epoch_impl = (
                "fused_scan_mxu"
                if exact_mxu_support_covers(weights.shape[-2])
                else "fused_scan"
            )
        else:
            epoch_impl = "xla"
    if epoch_impl in ("fused_scan", "fused_scan_mxu"):
        if mesh is not None:
            raise ValueError(
                "the fused case scan is a single-core Pallas program; "
                "miner-axis sharding requires epoch_impl='xla'"
            )
        if not consensus_auto and consensus_impl != "bisect":
            raise ValueError(
                "the fused case scan computes consensus by bisection; "
                f"consensus_impl={consensus_impl!r} requires epoch_impl='xla'"
            )
        ys = _simulate_case_fused(
            weights,
            stakes,
            reset_index,
            reset_epoch,
            config,
            spec,
            save_bonds=save_bonds,
            save_incentives=save_incentives,
            save_consensus=save_consensus,
            mxu=epoch_impl == "fused_scan_mxu",
        )
    elif epoch_impl == "xla":
        if consensus_auto:
            from yuma_simulation_tpu.ops.consensus import resolve_consensus_impl

            consensus_impl = resolve_consensus_impl(
                consensus_impl, *weights.shape[-2:]
            )
        if mesh is not None:
            axis = mesh.axis_names[-1]
            weights = jax.device_put(
                weights, NamedSharding(mesh, PartitionSpec(None, None, axis))
            )
        ys = _simulate_scan(
            weights,
            stakes,
            reset_index,
            reset_epoch,
            config,
            spec,
            save_bonds=save_bonds,
            save_incentives=save_incentives,
            save_consensus=save_consensus,
            consensus_impl=consensus_impl,
            mesh=mesh,
        )
    else:
        raise ValueError(
            f"unknown epoch_impl {epoch_impl!r}; "
            "expected 'auto', 'xla', 'fused_scan' or 'fused_scan_mxu'"
        )
    ys = jax.device_get(ys)
    return SimulationResult(
        dividends=ys["dividends"],
        bonds=ys.get("bonds"),
        incentives=ys.get("incentives"),
        consensus=ys.get("consensus"),
    )


def run_simulation(
    case: Scenario,
    yuma_version: str,
    yuma_config: Optional[YumaConfig] = None,
) -> tuple[dict[str, list[float]], list[np.ndarray], list[np.ndarray]]:
    """Drop-in equivalent of the reference driver
    (simulation_utils.py:26-112): returns `(dividends_per_validator,
    bonds_per_epoch, server_incentives_per_epoch)` with numpy arrays in
    place of torch tensors.
    """
    result = simulate(case, yuma_version, yuma_config)
    dividends_per_validator = {
        validator: [float(x) for x in result.dividends[:, i]]
        for i, validator in enumerate(case.validators)
    }
    assert result.bonds is not None and result.incentives is not None
    bonds_per_epoch = list(result.bonds)
    server_incentives_per_epoch = list(result.incentives)
    return dividends_per_validator, bonds_per_epoch, server_incentives_per_epoch


@partial(
    jax.jit,
    static_argnames=("spec", "consensus_impl", "epoch_impl"),
)
def simulate_scaled(
    W: jnp.ndarray,  # [V, M] base weights
    S: jnp.ndarray,  # [V]
    scales: jnp.ndarray,  # [E] per-epoch weight scale (epoch e uses W*scales[e])
    config: YumaConfig,
    spec: VariantSpec,
    consensus_impl: str = "bisect",
    epoch_impl: str = "xla",
):
    """Epoch-VARYING throughput workload: epoch `e` simulates `W*scales[e]`.

    This is the honest full-kernel benchmark path: because the weights
    differ every epoch, XLA cannot hoist any of the consensus front half
    out of the scan (with constant weights XLA's loop-invariant code
    motion silently hoists most of the kernel even when
    `hoist_invariant=False` — measured ~3x optimistic at 256x4096). The
    scalar scale is numerically almost-neutral (row normalization divides
    it back out) but is opaque to the compiler, so every epoch pays the
    full per-epoch cost exactly like a real changing-weights workload.

    `epoch_impl`:
      - "auto": pick the fastest *parity-safe* path — the
        single-Pallas-program scan when the variant/config/shape allow
        it (any bonds model incl. liquid alpha, quantile overrides,
        Yuma-0 under x64, f32 arrays, fits the VMEM budget, on TPU,
        >= 1 epoch), otherwise the XLA path. Since r4 that means the
        MXU scan ("fused_scan_mxu") wherever the exact limb-split
        support covers V (<= 2^14): its consensus support is the exact
        canonical integer sum on the MXU and the whole scan is BITWISE
        the VPU scan, ~1.6x faster.
      - "xla": the unfused `yuma_epoch` (any variant/consensus_impl).
      - "fused": the Pallas VMEM-resident EMA-family epoch kernel
        (:func:`yuma_simulation_tpu.ops.pallas_epoch.fused_ema_epoch`),
        VPU reductions (matches XLA to ~1e-9).
      - "fused_mxu": same per-epoch kernel with the consensus support
        on the exact limb-split MXU contraction (bitwise the "fused"
        path since r4; requires V <= 2^14).
      - "fused_scan" / "fused_scan_mxu": the ENTIRE epoch scan as one
        Pallas program — bond state resident in VMEM scratch across grid
        steps, W fetched from HBM once, no per-epoch dispatch
        (:func:`yuma_simulation_tpu.ops.pallas_epoch.fused_ema_scan`).
        Covers all five bond models (capacity/relative included, unlike
        the per-epoch "fused" paths). The two are bitwise-identical
        (the MXU scan's support is the exact limb-split integer
        contraction); "fused_scan_mxu" is ~1.6x faster and needs
        V <= 2^14.

    Returns `(total_dividends[V], final_bonds[V, M])` like
    `simulate_constant`.
    """
    V, M = W.shape
    dtype = W.dtype
    # The fused branches bisect in-kernel and never read consensus_impl,
    # but resolve/validate it unconditionally so "auto" works and typos
    # raise on every path (one shared contract, ops/consensus.py).
    from yuma_simulation_tpu.ops.consensus import resolve_consensus_impl

    consensus_impl = resolve_consensus_impl(consensus_impl, V, M)

    def to_dividends(D_n):
        return _dividends_per_1k(D_n, S, config, dtype)

    if epoch_impl == "auto":
        from yuma_simulation_tpu.ops.pallas_epoch import (
            exact_mxu_support_covers,
            fused_scan_eligible,
        )

        # Since r4 the MXU scan's consensus support is EXACT (limb-split
        # integer contraction) and the whole scan is bitwise the VPU
        # scan, so auto prefers it wherever the limb split covers V.
        # E=0 falls back to XLA, which returns zeros.
        if scales.shape[0] >= 1 and fused_scan_eligible(
            W.shape, spec.bonds_mode, config, W.dtype
        ):
            epoch_impl = (
                "fused_scan_mxu"
                if exact_mxu_support_covers(V)
                else "fused_scan"
            )
        else:
            epoch_impl = "xla"

    if epoch_impl in ("fused_scan", "fused_scan_mxu"):
        from yuma_simulation_tpu.ops.pallas_epoch import fused_ema_scan

        B_final, D_tot = fused_ema_scan(
            W,
            S / S.sum(),
            scales,
            mode=spec.bonds_mode,
            mxu=epoch_impl == "fused_scan_mxu",
            **fused_hparams(config),
        )
        # The per-1000-tao conversion is linear in D_n, so applying it to
        # the in-kernel epoch sum equals summing per-epoch conversions.
        return to_dividends(D_tot), B_final

    if epoch_impl in ("fused", "fused_mxu"):
        from yuma_simulation_tpu.ops.pallas_epoch import fused_ema_epoch

        if spec.bonds_mode not in _EMA_MODES:
            raise ValueError("fused epoch_impl supports the EMA family only")
        if config.liquid_alpha:
            raise ValueError("fused epoch_impl does not support liquid alpha")
        mxu = epoch_impl == "fused_mxu"
        S_n = S / S.sum()  # stake is epoch-constant; normalize once
        # fused_ema_epoch takes only the EMA-family subset of the shared
        # mapping (no capacity/decay/liquid fields) — still sourced from
        # the one helper so the spellings cannot drift between impls.
        hp = fused_hparams(config)
        ema_hp = {k: hp[k] for k in ("kappa", "bond_penalty", "bond_alpha", "precision")}

        def epoch_body(B, W_prev, scale, first):
            clip = None
            if spec.bonds_mode is BondsMode.EMA_PREV:
                W_n_now = normalize_weight_rows(W * scale)
                clip = jnp.where(first, W_n_now, W_prev)
            B_next, D_n, _ = fused_ema_epoch(
                W,
                S_n,
                B,
                w_scale=scale,
                first_epoch=first,
                clip_base=clip,
                mode=spec.bonds_mode,
                mxu=mxu,
                **ema_hp,
            )
            return B_next, normalize_weight_rows(W * scale), D_n

    else:
        if epoch_impl != "xla":
            # A typo'd/unknown impl must not silently benchmark the XLA
            # path under the wrong label (simulate() validates the same
            # way).
            raise ValueError(
                f"unknown epoch_impl {epoch_impl!r}; expected 'auto', "
                "'xla', 'fused', 'fused_mxu', 'fused_scan' or "
                "'fused_scan_mxu'"
            )

        def epoch_body(B, W_prev, scale, first):
            Wv = W * scale
            kernel_prev = None
            if spec.bonds_mode is BondsMode.EMA_PREV:
                kernel_prev = jnp.where(
                    first, normalize_weight_rows(Wv), W_prev
                )
            res = yuma_epoch(
                Wv,
                S,
                B,
                config,
                bonds_mode=spec.bonds_mode,
                W_prev=kernel_prev,
                first_epoch=first,
                consensus_impl=consensus_impl,
            )
            return (
                res[spec.bond_state_key],
                res["weight"],
                res["validator_reward_normalized"],
            )

    carries_prev = spec.carries_prev_weights

    def step(carry, xs):
        if carries_prev:
            B, W_prev, acc = carry
        else:
            (B, acc), W_prev = carry, None
        scale, epoch = xs
        B_next, W_n_now, D_n = epoch_body(B, W_prev, scale, epoch == 0)
        acc = acc + to_dividends(D_n)
        if carries_prev:
            return (B_next, W_n_now, acc), None
        return (B_next, acc), None

    E = scales.shape[0]
    zero_b = jnp.zeros((V, M), dtype)
    zero_acc = jnp.zeros((V,), dtype)
    carry0 = (
        (zero_b, zero_b, zero_acc) if carries_prev else (zero_b, zero_acc)
    )
    final, _ = lax.scan(
        step, carry0, (scales, jnp.arange(E, dtype=jnp.int32))
    )
    return final[-1], final[0]


@partial(
    jax.jit,
    static_argnames=("spec", "consensus_impl", "epoch_impl"),
)
def simulate_scaled_batch(
    W: jnp.ndarray,  # [B, V, M] per-scenario base weights
    S: jnp.ndarray,  # [B, V]
    scales: jnp.ndarray,  # [E] shared per-epoch weight scale
    config: YumaConfig,
    spec: VariantSpec,
    consensus_impl: str = "bisect",
    epoch_impl: str = "xla",
):
    """A scenario batch of the epoch-varying throughput workload
    (:func:`simulate_scaled`), sharing one compiled program.

    A single 256x4096 run keeps the chip a few percent utilized
    (DESIGN.md "Utilization"): each of the ~45 VPU passes per epoch is
    latency- not bandwidth-bound at that size, and they are sequentially
    dependent. Batching advances all `B` scenarios together so every
    pass works on `B`-fold data — the chip-filling configuration for
    varying-weights work.

    `epoch_impl`: "xla" (`vmap` over the per-scenario scan),
    "fused_scan" (the batched single-Pallas-program scan, VPU
    reductions), or "fused_scan_mxu" (same scan with the exact
    limb-split MXU support — bitwise-identical, the batch rides the
    dot's batch dimensions; V <= 2^14). "auto" picks the MXU scan when
    eligible on this backend, else the VPU scan, else XLA.

    `config` may carry batched `[B]` float leaves (a
    :func:`..simulation.sweep.config_grid` grid): the fused path ships
    them to the kernel as per-scenario hyperparameter vectors (ONE
    dispatch for the whole grid) and the XLA path vmaps over them.

    Returns `(total_dividends [B, V], final_bonds [B, V, M])`.
    """
    from yuma_simulation_tpu.ops.consensus import resolve_consensus_impl

    consensus_impl = resolve_consensus_impl(consensus_impl, *W.shape[-2:])
    batched_cfg = config_is_batched(config)
    if epoch_impl == "auto":
        from yuma_simulation_tpu.ops.pallas_epoch import (
            exact_mxu_support_covers,
            fused_scan_eligible,
        )

        if scales.shape[0] >= 1 and fused_scan_eligible(
            W.shape, spec.bonds_mode, config, W.dtype
        ):
            epoch_impl = (
                "fused_scan_mxu"
                if exact_mxu_support_covers(W.shape[-2])
                else "fused_scan"
            )
        else:
            epoch_impl = "xla"
    if epoch_impl in ("fused_scan", "fused_scan_mxu"):
        from yuma_simulation_tpu.ops.pallas_epoch import fused_ema_scan

        B_final, D_tot = fused_ema_scan(
            W,
            S / S.sum(axis=-1, keepdims=True),
            scales,
            mode=spec.bonds_mode,
            mxu=epoch_impl == "fused_scan_mxu",
            **fused_hparams(config),
        )
        if batched_cfg:
            totals = jax.vmap(
                lambda d, s, c: _dividends_per_1k(d, s, c, W.dtype),
                in_axes=(0, 0, config_vmap_axes(config)),
            )(D_tot, S, config)
        else:
            totals = _dividends_per_1k(D_tot, S, config, W.dtype)
        return totals, B_final
    if epoch_impl != "xla":
        # A typo'd impl must not silently benchmark the XLA path under
        # the wrong label.
        raise ValueError(
            f"unknown epoch_impl {epoch_impl!r} for simulate_scaled_batch; "
            "expected 'auto', 'xla', 'fused_scan' or 'fused_scan_mxu'"
        )
    if batched_cfg:
        return jax.vmap(
            lambda w, s, c: simulate_scaled(
                w, s, scales, c, spec,
                consensus_impl=consensus_impl, epoch_impl="xla",
            ),
            in_axes=(0, 0, config_vmap_axes(config)),
        )(W, S, config)
    return jax.vmap(
        lambda w, s: simulate_scaled(
            w, s, scales, config, spec,
            consensus_impl=consensus_impl, epoch_impl="xla",
        )
    )(W, S)


@partial(
    jax.jit,
    static_argnames=(
        "num_epochs", "spec", "consensus_impl", "hoist_invariant", "mesh"
    ),
)
def simulate_constant(
    W: jnp.ndarray,  # [V, M], constant across epochs
    S: jnp.ndarray,  # [V]
    num_epochs: int,
    config: YumaConfig,
    spec: VariantSpec,
    consensus_impl: str = "bisect",
    hoist_invariant: bool = False,
    mesh: Optional[Mesh] = None,
):
    """Throughput path: fixed weights, total dividends accumulated in-carry.

    Returns `total_dividends[V]` (sum over epochs of dividend-per-1000-tao)
    and the final bond state. No per-epoch outputs are materialized, so 10k+
    epoch sweeps at 256x4096 stay well inside HBM.

    `num_epochs` must be >= 1 on the hoisted path (the plain scan form
    degenerates to zeros at 0 epochs; the hoisted form has no epoch to
    seed from).

    `consensus_impl="auto"` resolves to the shape-gated sorted/bisect
    default at trace time (sorted below the documented compile-pathology
    threshold — the two produce bitwise-identical values).

    `hoist_invariant=True` exploits the constant weights: the consensus
    front half (normalize, bisection, quantize, clip, incentive, liquid
    alpha) depends only on `(W, S)`, so it runs once and the scan carries
    only the bonds recurrence + dividend conversion — the same update ops
    on the same values (agreement exact up to XLA's own fusion-dependent
    ULP at very short scan lengths), ~2x faster at 256x4096; XLA does not
    perform this hoist on its own.

    With ``mesh``, the miner axis is sharded over the mesh's last axis
    across the whole scan (both paths), for subnets beyond one chip's HBM.
    """
    # Static-arg resolution/validation at trace time: "auto" becomes the
    # shape-gated sorted/bisect default (bitwise twins; compile-cost
    # choice only), unknown strings raise.
    from yuma_simulation_tpu.ops.consensus import resolve_consensus_impl

    consensus_impl = resolve_consensus_impl(consensus_impl, *W.shape)
    if hoist_invariant:
        return _simulate_constant_hoisted(
            W, S, num_epochs, config, spec, consensus_impl, mesh
        )
    V, M = W.shape
    dtype = W.dtype
    shardings = None if mesh is None else _miner_shardings(mesh)
    if shardings is not None:
        W = lax.with_sharding_constraint(W, shardings[0])

    def step(carry, epoch):
        B, W_prev, C_prev, acc = carry
        first = epoch == 0
        if shardings is not None:
            vm, m = shardings
            B = lax.with_sharding_constraint(B, vm)
            W_prev = lax.with_sharding_constraint(W_prev, vm)
            C_prev = lax.with_sharding_constraint(C_prev, m)
        if spec.reset_mode is not ResetMode.NONE:
            B = _apply_reset(
                B, C_prev, epoch, jnp.int32(-1), jnp.int32(-1), spec.reset_mode, M
            )
        kernel_prev = None
        if spec.bonds_mode is BondsMode.EMA_PREV:
            kernel_prev = jnp.where(first, normalize_weight_rows(W), W_prev)
        res = yuma_epoch(
            W,
            S,
            B,
            config,
            bonds_mode=spec.bonds_mode,
            W_prev=kernel_prev,
            first_epoch=first,
            consensus_impl=consensus_impl,
        )
        dividends = _dividends_per_1k(
            res["validator_reward_normalized"], S, config, dtype
        )
        B_next = res[spec.bond_state_key]
        W_prev_next = res["weight"] if spec.carries_prev_weights else W_prev
        return (
            B_next,
            W_prev_next,
            res["server_consensus_weight"],
            acc + dividends,
        ), None

    carry0 = (
        jnp.zeros((V, M), dtype),
        jnp.zeros((V, M), dtype),
        jnp.zeros((M,), dtype),
        jnp.zeros((V,), dtype),
    )
    (B, _, _, total), _ = lax.scan(
        step, carry0, jnp.arange(num_epochs, dtype=jnp.int32)
    )
    return total, B


def _simulate_constant_hoisted(
    W, S, num_epochs: int, config: YumaConfig, spec: VariantSpec,
    consensus_impl: str, mesh: Optional[Mesh] = None,
):
    """Constant-weights fast path: one kernel front half + a bonds-only scan.

    Epoch 0 of the full kernel supplies every epoch-invariant quantity
    (normalized weights/stakes, consensus, clipped weights, incentive,
    liquid-alpha rate, and — for the EMA families — the purchase target);
    the scan then applies exactly the per-epoch update helpers the kernel
    itself uses (:mod:`yuma_simulation_tpu.models.epoch`). Bond resets
    don't apply (no scenario metadata in the constant path — as in
    `simulate_constant`'s reset-free scan).
    """
    if num_epochs < 1:
        raise ValueError("hoist_invariant path requires num_epochs >= 1")
    dtype = W.dtype
    shardings = None if mesh is None else _miner_shardings(mesh)
    if shardings is not None:
        W = lax.with_sharding_constraint(W, shardings[0])

    # Full kernel once; also the source of the final outputs' first step.
    res0 = yuma_epoch(
        W, S, None, config, bonds_mode=spec.bonds_mode,
        consensus_impl=consensus_impl,
    )
    W_n = res0["weight"]
    S_n = res0["stake"]
    incentive = res0["server_incentive"]
    # The EMA rate, exactly as the kernel derives it (epoch.py): the
    # liquid-alpha fit on this epoch's (invariant) consensus, else the
    # static scalar. RELATIVE mode doesn't export bond_alpha (the
    # reference's Yuma4 output dict has no such key, yumas.py:595-606),
    # so recompute rather than read it back.
    if config.liquid_alpha and spec.bonds_mode is not BondsMode.CAPACITY:
        rate, _, _ = liquid_alpha_rate(
            res0["server_consensus_weight"],
            config.alpha_low,
            config.alpha_high,
            override_consensus_high=config.override_consensus_high,
            override_consensus_low=config.override_consensus_low,
        )
    else:
        rate = jnp.asarray(config.bond_alpha, dtype)

    def dividends_of(B):
        if spec.bonds_mode is BondsMode.RELATIVE:
            D = S_n * (B * incentive).sum(axis=-1)
        else:
            D = (B * incentive).sum(axis=-1)
        D_n = D / (D.sum() + 1e-6)
        return _dividends_per_1k(D_n, S, config, dtype)

    pin = (
        (lambda B: lax.with_sharding_constraint(B, shardings[0]))
        if shardings is not None
        else (lambda B: B)
    )

    if spec.bonds_mode in _EMA_MODES:
        B_target = res0["validator_bond"]
        renorm = spec.bonds_mode is BondsMode.EMA_RUST

        def step(carry, _):
            B_ema, acc = carry
            B_next = pin(ema_bonds_update(B_target, pin(B_ema), rate, None, renorm))
            return (B_next, acc + dividends_of(B_next)), None

        B0 = res0["validator_ema_bond"]
    elif spec.bonds_mode is BondsMode.CAPACITY:

        def step(carry, _):
            B_prev, acc = carry
            B_next = pin(capacity_bonds_update(pin(B_prev), W_n, S_n, config))
            return (B_next, acc + dividends_of(B_next)), None

        B0 = res0["validator_bonds"]
    else:  # RELATIVE

        def step(carry, _):
            B_prev, acc = carry
            B_next = pin(relative_bonds_update(pin(B_prev), W_n, rate))
            return (B_next, acc + dividends_of(B_next)), None

        B0 = res0["validator_bonds"]

    acc0 = dividends_of(B0)
    if num_epochs == 1:
        return acc0, B0
    (B, total), _ = lax.scan(step, (B0, acc0), None, length=num_epochs - 1)
    return total, B
