"""Simulation engine: scan over epochs, vmap over scenarios/hyperparameters."""

from yuma_simulation_tpu.simulation.engine import (  # noqa: F401
    SimulationResult,
    run_simulation,
    simulate,
    simulate_constant,
    simulate_generated,
    simulate_streamed,
)
from yuma_simulation_tpu.simulation.sweep import (  # noqa: F401
    config_grid,
    simulate_batch,
    sweep_hyperparams,
)
