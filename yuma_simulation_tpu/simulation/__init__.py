"""Simulation engine: scan over epochs, vmap over scenarios/hyperparameters."""

from yuma_simulation_tpu.simulation.engine import (  # noqa: F401
    SimulationResult,
    run_simulation,
    simulate,
    simulate_constant,
    simulate_generated,
    simulate_streamed,
)
from yuma_simulation_tpu.simulation.planner import (  # noqa: F401
    DispatchPlan,
    plan_dispatch,
)
from yuma_simulation_tpu.simulation.sweep import (  # noqa: F401
    config_grid,
    pack_scenarios,
    simulate_batch,
    sweep_hyperparams,
)
