"""AOT executable cache: cold-start economics for every process tier.

Every process in the platform — serve workers, fleet hosts, supervisors,
CLIs, bench — dispatches the same jitted consensus kernels over the same
(V, M, epochs, engine) shape buckets, and each one re-pays the full
XLA/Mosaic compile on every start. Compile cost is *measured* everywhere
(the ``compile_seconds`` histogram, the cold-start SLO, Server-Timing
compile spans) but amortized nowhere. This module is the amortization:

- a **content-addressed on-disk executable cache**: each planner-rung
  program is AOT-lowered and serialized with ``jax.export`` under a key
  derived from the HLO sha256 fingerprint ``telemetry/cost.py`` already
  computes, composed with the backend / device kind / jax / jaxlib
  versions — a toolchain or device change makes stale entries MISS
  instead of misexecute;
- a **dispatch seam** (:func:`dispatch_via_cache`, surfaced on
  :meth:`..simulation.planner.DispatchPlan.attach_executable`): on cache
  hit the engine dispatches the deserialized executable directly (no
  re-trace, no re-lower; the XLA compile of the deserialized module is
  served by the persistent compilation cache tier below); on miss it
  JITs exactly as today and *publishes* the serialized artifact through
  ``publish_atomic``, so concurrent writers race safely and the next
  process start is warm;
- the **persistent JAX compilation cache** as the fallback tier:
  :func:`configure_executable_cache` enables
  ``jax_compilation_cache_dir`` beside the artifact store (min compile
  time 0 — a cold-start cache that only persists minutes-scale compiles
  would leave every CPU lane cold), so even programs the executable
  cache does not cover skip their XLA compile on the second start.

Every load outcome is a typed event — ``executable_cache_hit`` /
``executable_cache_miss`` (with a ``reason``: absent, corrupt, torn,
undeserializable) / ``executable_cache_stale`` (an artifact for this
exact program exists, built under a different toolchain/device) — plus
registry counters, so a fleet's cache effectiveness is a metrics query,
not a guess. A corrupt or truncated artifact is ALWAYS a typed miss that
requeues to the JIT path; it can never crash a dispatch or serve a wrong
program (the digest check rejects torn bytes before deserialization).

Parity is the gate: an AOT-dispatched result must be bitwise-identical
to the JIT path (tests/unit/test_aot.py pins every planner rung on the
bucket grid), which holds by construction — the serialized artifact IS
the jit-lowered program, round-tripped through StableHLO.

The cache is OFF unless configured (:func:`configure_executable_cache`,
the ``--executable-cache`` CLI flags, or the
:data:`EXECUTABLE_CACHE_ENV` environment variable), so the zero-compile
sentinels and bitwise pins of the existing test surface run the exact
legacy path by default.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import pathlib
import threading
from typing import Callable, Optional, Sequence

from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)

#: Environment variable naming the cache directory: processes that take
#: no CLI flag (bench subprocesses, ad-hoc scripts) join the cache by
#: exporting this.
EXECUTABLE_CACHE_ENV = "YUMA_TPU_EXECUTABLE_CACHE"

#: Artifact subdirectory under the cache root (the sibling ``xla/`` holds
#: the persistent-compilation-cache tier).
ARTIFACT_SUBDIR = "aot"

#: Stats artifact name (:meth:`ExecutableCache.write_stats`) — the CI
#: cold-start lane asserts on the second run's copy.
STATS_FILENAME = "cache_stats.json"


# ---------------------------------------------------------------------------
# export serialization of the package's pytree nodes


#: Pytree dataclasses that may appear in a dispatch's input/output trees.
#: ``jax.export`` serialization refuses unregistered node types, so each
#: is registered once with a stable name; auxdata (the static-field
#: tuple of ``register_dataclass``) round-trips through JSON with
#: list->tuple restoration (the flatten contract wants tuples back).
_EXPORT_PYTREE_TYPES_DONE = False
_EXPORT_LOCK = threading.Lock()


def _auxdata_from_json(raw: bytes):
    def detuple(v):
        if isinstance(v, list):
            return tuple(detuple(x) for x in v)
        return v

    return detuple(json.loads(raw.decode()))


def register_export_serialization() -> None:
    """Register the package's pytree dataclasses with ``jax.export``
    serialization (idempotent; re-registration errors are swallowed —
    another caller already did the work)."""
    global _EXPORT_PYTREE_TYPES_DONE
    with _EXPORT_LOCK:
        if _EXPORT_PYTREE_TYPES_DONE:
            return
        from jax import export as jax_export

        from yuma_simulation_tpu.models.config import (
            SimulationHyperparameters,
            YumaConfig,
            YumaParams,
        )
        from yuma_simulation_tpu.simulation.carry import NumericsSketch

        for cls in (
            SimulationHyperparameters,
            YumaParams,
            YumaConfig,
            NumericsSketch,
        ):
            try:
                jax_export.register_pytree_node_serialization(
                    cls,
                    serialized_name=f"yuma_simulation_tpu.{cls.__name__}",
                    serialize_auxdata=lambda aux: json.dumps(aux).encode(),
                    deserialize_auxdata=_auxdata_from_json,
                )
            except ValueError:
                # Already registered (a prior cache instance in this
                # process) — the registration is process-global.
                pass
        _EXPORT_PYTREE_TYPES_DONE = True


# ---------------------------------------------------------------------------
# environment key: what must match for an artifact to be executable here


def environment_descriptor() -> dict:
    """The toolchain/device coordinates an artifact is only valid under.
    Composed into every cache key: a jax/jaxlib upgrade or a different
    device kind turns yesterday's artifacts into typed stale misses
    instead of programs that deserialize into the wrong runtime."""
    import jax
    import jaxlib

    from yuma_simulation_tpu.telemetry.cost import _probe_device

    kind, _ = _probe_device()
    return {
        "backend": jax.default_backend(),
        "device_kind": kind or "unknown",
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
    }


def _environment_key(env: dict) -> str:
    return hashlib.sha256(
        json.dumps(env, sort_keys=True).encode()
    ).hexdigest()[:16]


# ---------------------------------------------------------------------------
# stats


@dataclasses.dataclass
class AotStats:
    """Process-lifetime cache effectiveness counters. ``hits`` counts
    artifacts loaded from disk (one per program per process — further
    dispatches ride the in-process memo silently); ``builds`` counts
    true AOT compiles (a miss that exported + published); ``errors``
    counts load/build failures that fell back to the plain JIT path."""

    hits: int = 0
    misses: int = 0
    stale: int = 0
    builds: int = 0
    errors: int = 0

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# the on-disk cache


class ExecutableCache:
    """Content-addressed executable artifacts under ``root/aot/``.

    Layout: one directory per full HLO sha256 fingerprint, one
    ``<envkey>.bin`` (serialized ``jax.export.Exported``) plus
    ``<envkey>.json`` metadata per environment. The metadata is
    published LAST (both through ``publish_atomic``), so a reader that
    sees the metadata sees a complete artifact; the blob digest recorded
    there rejects corrupt/truncated bytes before deserialization."""

    def __init__(self, root: str | pathlib.Path):
        self.root = pathlib.Path(root)
        self.artifact_dir = self.root / ARTIFACT_SUBDIR
        self.env = environment_descriptor()
        self.env_key = _environment_key(self.env)
        self.stats = AotStats()
        # Registry counters created ONCE with literal names (the
        # jaxlint JX202 contract); metrics must never break a dispatch.
        try:
            from yuma_simulation_tpu.telemetry.metrics import get_registry

            registry = get_registry()
            self._counters = {
                "hits": registry.counter("executable_cache_hits"),
                "misses": registry.counter("executable_cache_misses"),
                "stale": registry.counter("executable_cache_stale"),
                "builds": registry.counter("executable_cache_builds"),
            }
        except Exception:
            self._counters = {}

    # -- paths ---------------------------------------------------------

    def _entry_dir(self, fingerprint: str) -> pathlib.Path:
        return self.artifact_dir / fingerprint

    def _blob_path(self, fingerprint: str) -> pathlib.Path:
        return self._entry_dir(fingerprint) / f"{self.env_key}.bin"

    def _meta_path(self, fingerprint: str) -> pathlib.Path:
        return self._entry_dir(fingerprint) / f"{self.env_key}.json"

    # -- counters ------------------------------------------------------

    def _count(self, key: str) -> None:
        counter = self._counters.get(key)
        if counter is not None:
            counter.inc()

    def _miss(self, fingerprint: str, label: str, reason: str) -> None:
        self.stats.misses += 1
        self._count("misses")
        log_event(
            logger,
            "executable_cache_miss",
            level=logging.DEBUG if reason == "absent" else logging.INFO,
            fingerprint=fingerprint[:16],
            label=label,
            reason=reason,
        )

    # -- load / store --------------------------------------------------

    def load(self, fingerprint: str, *, label: str = ""):
        """The deserialized ``jax.export.Exported`` for `fingerprint`
        under THIS environment, or None with exactly one typed event
        saying why: ``executable_cache_stale`` when artifacts for this
        program exist only under other toolchains/devices,
        ``executable_cache_miss`` (reason absent/torn/corrupt/
        undeserializable) otherwise. Never raises — a bad artifact
        requeues the dispatch to the JIT path."""
        register_export_serialization()
        blob_path = self._blob_path(fingerprint)
        meta_path = self._meta_path(fingerprint)
        if not meta_path.exists():
            entry = self._entry_dir(fingerprint)
            try:
                siblings = [
                    p for p in entry.glob("*.json")
                    if p.name != meta_path.name
                ]
            except OSError:
                siblings = []
            if siblings:
                self.stats.stale += 1
                self._count("stale")
                log_event(
                    logger,
                    "executable_cache_stale",
                    level=logging.INFO,
                    fingerprint=fingerprint[:16],
                    label=label,
                    foreign_artifacts=len(siblings),
                    env_key=self.env_key,
                )
            else:
                self._miss(fingerprint, label, "absent")
            return None
        try:
            meta = json.loads(meta_path.read_text())
        except (OSError, ValueError):
            self._miss(fingerprint, label, "torn_metadata")
            return None
        try:
            blob = blob_path.read_bytes()
        except OSError:
            self._miss(fingerprint, label, "blob_missing")
            return None
        if hashlib.sha256(blob).hexdigest() != meta.get("blob_sha256"):
            self._miss(fingerprint, label, "corrupt")
            return None
        if meta.get("environment") != self.env:
            # Belt and braces: the env key already namespaces the file,
            # so reaching here means a hash collision or a hand-copied
            # artifact — refuse it as stale rather than misexecute.
            self.stats.stale += 1
            self._count("stale")
            log_event(
                logger,
                "executable_cache_stale",
                level=logging.INFO,
                fingerprint=fingerprint[:16],
                label=label,
                env_key=self.env_key,
            )
            return None
        try:
            from jax import export as jax_export

            exported = jax_export.deserialize(blob)
        except Exception as e:
            self._miss(
                fingerprint, label, f"undeserializable:{type(e).__name__}"
            )
            return None
        self.stats.hits += 1
        self._count("hits")
        log_event(
            logger,
            "executable_cache_hit",
            level=logging.INFO,
            fingerprint=fingerprint[:16],
            label=label,
            bytes=len(blob),
        )
        return exported

    def store(self, fingerprint: str, exported, *, label: str = "") -> bool:
        """Serialize and publish one artifact (crash-safe, last-writer-
        wins-whole via ``publish_atomic`` — concurrent builders of the
        same program cannot interleave bytes). Returns False (with an
        error counted) instead of raising: publishing is an
        optimization, never a dispatch dependency."""
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        register_export_serialization()
        try:
            blob = exported.serialize()
            entry = self._entry_dir(fingerprint)
            entry.mkdir(parents=True, exist_ok=True)
            publish_atomic(self._blob_path(fingerprint), blob)
            meta = {
                "fingerprint": fingerprint,
                "environment": self.env,
                "blob_sha256": hashlib.sha256(blob).hexdigest(),
                "blob_bytes": len(blob),
                "label": label,
            }
            publish_atomic(
                self._meta_path(fingerprint),
                json.dumps(meta, sort_keys=True).encode(),
            )
        except Exception:
            self.stats.errors += 1
            logger.warning(
                "executable cache publish failed for %s", label,
                exc_info=True,
            )
            return False
        return True

    # -- stats artifact ------------------------------------------------

    def entries_on_disk(self) -> int:
        try:
            return sum(
                1 for _ in self.artifact_dir.glob("*/*.bin")
            )
        except OSError:
            return 0

    def stats_payload(self) -> dict:
        return {
            **self.stats.to_json(),
            "environment": self.env,
            "env_key": self.env_key,
            "entries_on_disk": self.entries_on_disk(),
            "root": str(self.root),
        }

    def write_stats(self, path: Optional[str | pathlib.Path] = None) -> dict:
        """Publish the process's cache-effectiveness stats (the CI
        cold-start lane's artifact: run 2 must show ``builds == 0``,
        ``misses == 0`` and ``hits >= 1``)."""
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        payload = self.stats_payload()
        target = (
            pathlib.Path(path) if path is not None
            else self.root / STATS_FILENAME
        )
        target.parent.mkdir(parents=True, exist_ok=True)
        publish_atomic(
            target, json.dumps(payload, indent=2, sort_keys=True).encode()
        )
        return payload


# ---------------------------------------------------------------------------
# process-global activation + in-process memo


_ACTIVE: Optional[ExecutableCache] = None
_MEMO: dict = {}
_MEMO_LOCK = threading.Lock()

#: Cumulative stats of every cache this process has retired (replaced
#: or deactivated): :func:`process_stats` reports retired + active, so
#: RecompilationSentinel's entry/exit deltas stay monotonic even when a
#: region swaps the active cache mid-flight (a FleetHost/serve
#: construction inside a pinned region must not reset the build count
#: a budget is measured against).
_RETIRED_STATS = AotStats()

#: Environment value whose auto-configuration failed — remembered so a
#: bad YUMA_TPU_EXECUTABLE_CACHE path degrades to "no cache" ONCE
#: instead of re-raising (or re-attempting mkdir) on every dispatch.
_ENV_FAILED: Optional[str] = None

#: Negative-memo sentinel: a program that failed to lower/export once
#: (e.g. an interpret-mode Pallas rung off-TPU) must not re-pay the
#: failed attempt's tracing on every subsequent dispatch.
_UNRESOLVABLE = object()


def configure_executable_cache(
    root: str | pathlib.Path, *, persistent_compilation_cache: bool = True
) -> ExecutableCache:
    """Activate the process-global executable cache at `root` and (by
    default) enable JAX's persistent compilation cache beside it
    (``root/xla``) as the fallback tier — with min compile time 0, so
    the sub-second CPU compiles of the CI lanes persist too. Replaces
    any previously active cache (the in-process memo is kept: already-
    loaded executables stay valid, they are keyed by program content)."""
    global _ACTIVE
    cache = ExecutableCache(root)
    cache.artifact_dir.mkdir(parents=True, exist_ok=True)
    if persistent_compilation_cache:
        from yuma_simulation_tpu.utils.profiling import (
            enable_compilation_cache,
        )

        enable_compilation_cache(
            str(cache.root / "xla"), min_compile_secs=0.0
        )
    if _ACTIVE is not None:
        _retire(_ACTIVE.stats)
    _ACTIVE = cache
    return cache


def _retire(stats: AotStats) -> None:
    for field in dataclasses.fields(AotStats):
        setattr(
            _RETIRED_STATS,
            field.name,
            getattr(_RETIRED_STATS, field.name)
            + getattr(stats, field.name),
        )


def deactivate_executable_cache() -> None:
    """Deactivate the cache AND drop the in-process memo (tests)."""
    global _ACTIVE
    if _ACTIVE is not None:
        _retire(_ACTIVE.stats)
    _ACTIVE = None
    with _MEMO_LOCK:
        _MEMO.clear()


def active_cache() -> Optional[ExecutableCache]:
    """The process-global cache: an explicitly configured one, else one
    auto-configured from :data:`EXECUTABLE_CACHE_ENV`, else None (the
    seam is a no-op and every dispatch JITs exactly as before). An env
    path that fails to configure (typo, read-only filesystem) degrades
    to None with ONE warning — it must never crash a dispatch."""
    global _ENV_FAILED
    if _ACTIVE is not None:
        return _ACTIVE
    root = os.environ.get(EXECUTABLE_CACHE_ENV)
    if root and root != _ENV_FAILED:
        try:
            return configure_executable_cache(root)
        except Exception:
            _ENV_FAILED = root
            logger.warning(
                "%s=%r could not be configured; executable cache "
                "disabled for this process",
                EXECUTABLE_CACHE_ENV,
                root,
                exc_info=True,
            )
    return None


def process_stats() -> AotStats:
    """PROCESS-cumulative cache stats: every retired cache's tallies
    plus the active one's — what
    :class:`..utils.profiling.RecompilationSentinel` snapshots to tell
    cache-hit loads from true compiles (monotonic across cache swaps,
    so entry/exit deltas never go negative)."""
    total = dataclasses.replace(_RETIRED_STATS)
    cache = _ACTIVE
    if cache is not None:
        for field in dataclasses.fields(AotStats):
            setattr(
                total,
                field.name,
                getattr(total, field.name)
                + getattr(cache.stats, field.name),
            )
    return total


# ---------------------------------------------------------------------------
# the dispatch seam


@dataclasses.dataclass
class AotExecutable:
    """One resolved executable: ``call`` takes the DYNAMIC arguments of
    the original jitted function (statics are baked into the exported
    program). ``source`` is "cache" (deserialized from disk) or "built"
    (AOT-exported this process — a true compile)."""

    call: Callable
    fingerprint: str
    source: str
    label: str

    def describe(self) -> dict:
        return {
            "fingerprint": self.fingerprint[:16],
            "source": self.source,
            "label": self.label,
        }


def _leaf_token(leaf) -> str:
    aval = getattr(leaf, "aval", None)
    if aval is not None:  # jax.Array
        return aval.str_short()
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:  # np.ndarray / np scalar
        return f"{dtype}{list(shape)}"
    if isinstance(leaf, bool) or leaf is None or isinstance(leaf, str):
        return repr(leaf)
    if isinstance(leaf, (int, float, complex)):
        # Dynamic python scalars trace weak-typed: the VALUE does not
        # change the program, so it must not change the memo key.
        return f"py_{type(leaf).__name__}"
    return repr(leaf)


def _signature(
    fn, args: tuple, kwargs: dict, static_names: tuple = ()
) -> str:
    """The in-process memo key: function identity + static VALUES +
    dynamic input tree structure + per-leaf abstract tokens. Statics
    hash by value (they select the compiled program — an int static of
    0 vs 7 bakes two different programs); dynamic scalars are
    value-erased (a traced weak scalar's value never changes the
    program, and hashing it would fragment the memo per config value).
    Two calls with the same signature lower to the same program, so the
    signature resolves to one executable without re-tracing."""
    import jax

    statics = {k: v for k, v in kwargs.items() if k in static_names}
    dynamic = {k: v for k, v in kwargs.items() if k not in static_names}
    leaves, treedef = jax.tree.flatten((args, dict(sorted(dynamic.items()))))
    name = getattr(fn, "__name__", None) or repr(fn)
    parts = (
        [name, repr(sorted(statics.items())), str(treedef)]
        + [_leaf_token(leaf) for leaf in leaves]
    )
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def _load_or_build(
    cache: Optional[ExecutableCache],
    fn,
    args: tuple,
    kwargs: dict,
    label: str,
) -> Optional[AotExecutable]:
    """Resolve one program: fingerprint via the jit lowering (tracing
    only — no XLA compile), then disk load, else AOT-export + publish.
    `cache=None` resolves memo-only (export + wrap, nothing touches
    disk — the no-active-cache ``attach_executable`` path). Any failure
    returns None (error counted) and the caller JITs as today — the
    cache can slow nothing down and break nothing."""
    import jax
    from jax import export as jax_export

    from yuma_simulation_tpu.telemetry.cost import hlo_fingerprint

    register_export_serialization()
    try:
        lowered = fn.lower(*args, **kwargs)
        fingerprint = hlo_fingerprint(lowered, digits=None)
    except Exception:
        if cache is not None:
            cache.stats.errors += 1
        logger.debug("AOT lowering failed for %s", label, exc_info=True)
        return None
    exported = cache.load(fingerprint, label=label) if cache else None
    source = "cache"
    if exported is None:
        try:
            exported = jax_export.export(fn)(*args, **kwargs)
        except Exception:
            if cache is not None:
                cache.stats.errors += 1
            logger.debug("AOT export failed for %s", label, exc_info=True)
            return None
        if cache is not None:
            # The build is counted on the successful EXPORT, not the
            # publish: the compile happened regardless of whether the
            # artifact landed (a full/read-only cache disk must not
            # hide true compiles from RecompilationSentinel budgets).
            cache.stats.builds += 1
            cache._count("builds")
            cache.store(fingerprint, exported, label=label)
        source = "built"
    call = jax.jit(exported.call)
    return AotExecutable(
        call=call, fingerprint=fingerprint, source=source, label=label
    )


def dispatch_via_cache(
    fn,
    args: tuple,
    kwargs: dict,
    *,
    static_names: tuple,
    label: str,
):
    """The engine seam: dispatch `fn(*args, **kwargs)` through the
    executable cache, or return None meaning "ineligible — JIT exactly
    as today". Contract: `args` are the dynamic positional operands,
    `kwargs` may mix dynamic and static keywords, and `static_names`
    lists the static ones (they are baked into the exported program and
    dropped from the executable's call).

    No-ops (None) when no cache is active or under an ambient trace
    (``simulate_batch`` re-enters dispatch inside the ``shard_map``
    trace, where a host-side cache lookup would bake garbage into the
    program)."""
    cache = active_cache()
    if cache is None:
        return None
    from yuma_simulation_tpu.telemetry.runctx import _tracing_now

    if _tracing_now():
        return None
    sig = _signature(fn, args, kwargs, static_names)
    with _MEMO_LOCK:
        exe = _MEMO.get(sig)
    if exe is _UNRESOLVABLE:
        return None
    if exe is None:
        exe = _load_or_build(cache, fn, args, kwargs, label)
        with _MEMO_LOCK:
            _MEMO.setdefault(sig, exe if exe is not None else _UNRESOLVABLE)
        if exe is None:
            return None
    dynamic_kwargs = {
        k: v for k, v in kwargs.items() if k not in static_names
    }
    return exe.call(*args, **dynamic_kwargs)


# ---------------------------------------------------------------------------
# plan-level resolution (DispatchPlan.attach_executable's back half)


def executable_for_plan(
    plan,
    yuma_version: str = "Yuma 1 (paper)",
    *,
    cache: Optional[ExecutableCache] = None,
    config=None,
    dtype=None,
    save_bonds: bool = False,
    save_incentives: bool = False,
    quarantine: bool = False,
    batched: Optional[bool] = None,
) -> Optional[AotExecutable]:
    """Resolve (load, or AOT-build and publish) the executable for a
    :class:`..simulation.planner.DispatchPlan`'s engine rung at its
    bucket shape — the explicit preload seam warmup and the fleet hosts
    use, sharing the disk artifacts and the in-process memo with the hot
    path. Explicit-call only: a miss COMPILES, exactly like
    ``attach_cost``. With no cache active the executable is resolved
    memo-only (nothing touches disk). Returns None when the rung cannot
    be resolved on this backend (the caller's warmup falls back to a
    plain dispatch)."""
    import jax.numpy as jnp

    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.models.variants import variant_for_version
    from yuma_simulation_tpu.telemetry.numerics import numerics_enabled

    target = cache if cache is not None else active_cache()
    config = config if config is not None else YumaConfig()
    dtype = jnp.dtype(dtype if dtype is not None else jnp.float32)
    spec = variant_for_version(yuma_version)
    bucket = plan.bucket
    E = max(1, int(bucket.epochs))
    V, M = int(bucket.V), int(bucket.M)
    B = int(bucket.batch)
    # `batched=True` forces the BATCHED program even at one lane (a
    # fleet unit of width 1 still dispatches [1, E, V, M] through
    # `_simulate_batch_xla` — the bucket alone cannot tell the two
    # apart); default: batched exactly when the bucket carries lanes.
    batched = (B > 1) if batched is None else batched
    capture = numerics_enabled()
    ri_shape = (B,) if batched else ()
    W = jnp.zeros(((B,) if batched else ()) + (E, V, M), dtype)
    S = jnp.ones(((B,) if batched else ()) + (E, V), dtype)
    ri = jnp.full(ri_shape, -1, jnp.int32)
    re = jnp.full(ri_shape, -1, jnp.int32)
    from yuma_simulation_tpu.simulation.planner import (
        FUSED_CASE_RUNGS,
        rung_flags,
    )

    if plan.engine in FUSED_CASE_RUNGS:
        from yuma_simulation_tpu.simulation.engine import (
            _simulate_case_fused,
        )

        fn = _simulate_case_fused
        kwargs = dict(
            spec=spec,
            save_bonds=save_bonds,
            save_incentives=save_incentives,
            save_consensus=False,
            capture_numerics=capture,
            **rung_flags(plan.engine),
        )
        static_names = tuple(kwargs)
    elif batched:
        from yuma_simulation_tpu.simulation.sweep import _simulate_batch_xla

        # Mirror simulate_batch's seam exactly (statics AND the dynamic
        # miner_mask=None keyword): a preloaded unit-shaped executable
        # must be THE program the fleet/serve dispatch resolves, or the
        # preload warms nothing.
        fn = _simulate_batch_xla
        kwargs = dict(
            spec=spec,
            save_bonds=save_bonds,
            save_incentives=save_incentives,
            consensus_impl=plan.consensus_impl,
            guard_nonfinite=quarantine,
            capture_numerics=capture,
        )
        # miner_mask stays DYNAMIC — part of the exported call, not a
        # static — exactly as the simulate_batch seam spells it.
        static_names = tuple(kwargs)
        kwargs["miner_mask"] = None
    else:
        from yuma_simulation_tpu.simulation.engine import _simulate_scan

        fn = _simulate_scan
        kwargs = dict(
            spec=spec,
            save_bonds=save_bonds,
            save_incentives=save_incentives,
            save_consensus=False,
            consensus_impl=plan.consensus_impl,
            capture_numerics=capture,
        )
        static_names = tuple(kwargs)
    args = (W, S, ri, re, config)
    sig = _signature(fn, args, kwargs, static_names)
    with _MEMO_LOCK:
        exe = _MEMO.get(sig)
    if exe is _UNRESOLVABLE:
        return None
    if exe is not None:
        return exe
    exe = _load_or_build(target, fn, args, kwargs, label=plan.label)
    with _MEMO_LOCK:
        _MEMO.setdefault(sig, exe if exe is not None else _UNRESOLVABLE)
    return exe


def preload_shapes(
    shapes: Sequence[tuple],
    *,
    yuma_version: str = "Yuma 1 (paper)",
    batch: int = 1,
    quarantine: bool = False,
    config=None,
    dtype=None,
    batched: Optional[bool] = None,
    label: str = "preload",
) -> int:
    """Resolve executables for a set of ``(epochs, V, M)`` shape buckets
    before serving traffic / claiming a lease: cache hits load in
    milliseconds; misses pay the AOT build NOW — outside any request
    deadline or lease TTL — and publish for the next process.
    `config`/`dtype` must match the real dispatch's (they select the
    compiled program: a float32 preload warms nothing for a bfloat16
    fleet). Returns the number of buckets resolved. Failures are
    logged, never fatal."""
    import jax.numpy as jnp

    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.simulation.planner import plan_dispatch

    config = config if config is not None else YumaConfig()
    dtype = jnp.dtype(dtype if dtype is not None else jnp.float32)
    batched = (batch > 1) if batched is None else batched
    resolved = 0
    for shape in shapes:
        try:
            E, V, M = (int(d) for d in shape)
            dims = (batch, E, V, M) if batched else (E, V, M)
            plan = plan_dispatch(
                f"{label}:{E}x{V}x{M}",
                dims,
                yuma_version,
                config,
                dtype,
                quarantine=quarantine,
                check_memory=False,
            )
            if (
                executable_for_plan(
                    plan,
                    yuma_version,
                    quarantine=quarantine,
                    config=config,
                    dtype=dtype,
                    batched=batched,
                )
                is not None
            ):
                resolved += 1
        except Exception:
            logger.warning(
                "executable preload for shape %s failed", shape,
                exc_info=True,
            )
    return resolved
