"""Batched sweeps: `vmap` over scenarios and hyperparameter grids.

The reference sweeps with nested Python loops (bond_penalty x case x
version, reference scripts/*.py:14-16, v1/api.py:41-50), re-entering the
interpreter per combination. Here a sweep is one batched XLA computation:
scenarios stack on a leading axis, hyperparameters become batched config
pytree leaves, and the cross product is `vmap o vmap`. The same batched
callable is what `shard_map` partitions over the pod
(:mod:`yuma_simulation_tpu.parallel`).
"""

from __future__ import annotations

import itertools
from dataclasses import replace
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from yuma_simulation_tpu.models.config import (
    SimulationHyperparameters,
    YumaConfig,
    YumaParams,
)
from yuma_simulation_tpu.models.variants import VariantSpec, variant_for_version
from yuma_simulation_tpu.scenarios.base import Scenario
from yuma_simulation_tpu.simulation.engine import (
    _simulate_scan,
    config_is_batched,
    config_vmap_axes,
    simulate_constant,
)


def _reset_metadata(scenarios: Sequence[Scenario]):
    """`([B] reset_index, [B] reset_epoch)` with -1 sentinels for None."""
    r_idx = jnp.asarray(
        [-1 if s.reset_bonds_index is None else s.reset_bonds_index for s in scenarios],
        jnp.int32,
    )
    r_epoch = jnp.asarray(
        [-1 if s.reset_bonds_epoch is None else s.reset_bonds_epoch for s in scenarios],
        jnp.int32,
    )
    return r_idx, r_epoch


def pad_scenarios(
    scenarios: Sequence[Scenario],
    dtype=jnp.float32,
    *,
    pack_tiles: bool = False,
):
    """Pad a heterogeneous suite to a shared `[B, E, V, M]` shape.

    Padding is appended: extra epochs get zero weights *and* zero stakes
    (the dividend conversion's `stake > 1e-6` guard then yields exactly
    zero dividends for them, so totals are unchanged); extra validators
    get zero stake; extra miner columns get zero weight and are excluded
    from consensus quantization via the returned per-scenario miner mask
    (SURVEY.md §7 hard part (e): a padded column must not perturb the u16
    grid of real miners).

    `pack_tiles=True` is DONOR PACKING (the planner's shape-bucket
    policy, :func:`..simulation.planner.bucket_shape`): the shared shape
    is additionally rounded up to the (8, 128) f32 tile, so a small
    suite fills the vector/matrix unit's lanes instead of wasting them
    AND every suite whose raw shapes fall in the same bucket reuses one
    compiled batched program instead of tracing a program per ragged
    shape. The extra rows/columns ride exactly the padding mechanism
    above (zero stakes, mask-excluded miners), so packing is inert per
    lane — pinned by tests/unit/test_planner.py.

    Returns `(W[B,E,V,M], S[B,E,V], reset_index[B], reset_epoch[B],
    miner_mask[B,M])`.
    """
    E = max(s.weights.shape[0] for s in scenarios)
    V = max(s.weights.shape[1] for s in scenarios)
    M = max(s.weights.shape[2] for s in scenarios)
    if pack_tiles:
        from yuma_simulation_tpu.simulation.planner import bucket_shape

        bucket = bucket_shape(V, M, epochs=E, batch=len(scenarios))
        V, M = bucket.padded_V, bucket.padded_M
    B = len(scenarios)
    W = np.zeros((B, E, V, M), np.float32)
    S = np.zeros((B, E, V), np.float32)
    mask = np.zeros((B, M), np.float32)
    for i, s in enumerate(scenarios):
        e, v, m = s.weights.shape
        W[i, :e, :v, :m] = s.weights
        S[i, :e, :v] = s.stakes
        mask[i, :m] = 1.0
    r_idx, r_epoch = _reset_metadata(scenarios)
    return (
        jnp.asarray(W, dtype),
        jnp.asarray(S, dtype),
        r_idx,
        r_epoch,
        jnp.asarray(mask, dtype),
    )


def pack_scenarios(scenarios: Sequence[Scenario], dtype=jnp.float32):
    """Donor packing: one MXU-tile-filling padded batch for a small or
    heterogeneous suite — :func:`pad_scenarios` with the planner's
    tile-bucket policy on. The name is the contract: small scenarios
    donate their idle tile lanes to each other so the whole suite rides
    ONE batched dispatch on a bucket-reused compiled shape, instead of
    one dispatch (and one compiled program) per ragged case."""
    return pad_scenarios(scenarios, dtype, pack_tiles=True)


def stack_scenarios(scenarios: Sequence[Scenario], dtype=jnp.float32):
    """Stack same-shaped scenarios into `[B, E, V, M]` / `[B, E, V]` arrays
    plus reset metadata vectors. Heterogeneous suites must be padded first
    (padded miners get zero weights; padded validators zero stake)."""
    shapes = {s.weights.shape for s in scenarios}
    if len(shapes) != 1:
        raise ValueError(f"scenarios must share one [E,V,M] shape, got {shapes}")
    W = jnp.asarray(np.stack([s.weights for s in scenarios]), dtype)
    S = jnp.asarray(np.stack([s.stakes for s in scenarios]), dtype)
    r_idx, r_epoch = _reset_metadata(scenarios)
    return W, S, r_idx, r_epoch


@partial(
    jax.jit,
    static_argnames=(
        "spec",
        "save_bonds",
        "save_incentives",
        "consensus_impl",
        "guard_nonfinite",
        "capture_numerics",
    ),
)
def _simulate_batch_xla(
    weights,
    stakes,
    reset_index,
    reset_epoch,
    config,
    spec,
    save_bonds: bool,
    save_incentives: bool,
    consensus_impl: str,
    miner_mask=None,
    guard_nonfinite: bool = False,
    nan_fault_epochs=None,  # [B] i32, -1 = healthy lane (fault injection)
    capture_numerics: bool = False,
    drift_fault_epochs=None,  # [B] i32, -1 = healthy lane (drift canary)
):
    """The XLA rung of :func:`simulate_batch`: one `vmap` of the scan
    engine over the scenario axis (and batched config leaves), with the
    resilience knobs threaded per lane."""
    batched_cfg = config_is_batched(config)
    fn = lambda W, S, ri, re, mm, nf, df, cfg: _simulate_scan(  # noqa: E731
        W,
        S,
        ri,
        re,
        cfg,
        spec,
        save_bonds=save_bonds,
        save_incentives=save_incentives,
        save_consensus=False,
        consensus_impl=consensus_impl,
        miner_mask=mm,
        guard_nonfinite=guard_nonfinite,
        nan_fault_epoch=nf,
        capture_numerics=capture_numerics,
        drift_fault_epoch=df,
    )
    cfg_ax = config_vmap_axes(config) if batched_cfg else None
    mm_ax = None if miner_mask is None else 0
    nf_ax = None if nan_fault_epochs is None else 0
    df_ax = None if drift_fault_epochs is None else 0
    return jax.vmap(
        fn, in_axes=(0, 0, 0, 0, mm_ax, nf_ax, df_ax, cfg_ax)
    )(
        weights, stakes, reset_index, reset_epoch, miner_mask,
        nan_fault_epochs, drift_fault_epochs, config,
    )


def simulate_batch(
    weights: jnp.ndarray,  # [B, E, V, M]
    stakes: jnp.ndarray,  # [B, E, V]
    reset_index: jnp.ndarray,  # [B] int32
    reset_epoch: jnp.ndarray,  # [B] int32
    config: YumaConfig,
    spec: VariantSpec,
    save_bonds: bool = False,
    save_incentives: bool = False,
    consensus_impl: str = "bisect",
    miner_mask: Optional[jnp.ndarray] = None,  # [B, M] for padded suites
    epoch_impl: str = "xla",
    quarantine: bool = False,
    retry_policy=None,
    deadline=None,
):
    """A scenario suite in one computation.

    `epoch_impl`: "xla" (default — one `vmap` over the scenario axis;
    the engine the golden-pinned reporting paths use), "fused_scan" /
    "fused_scan_mxu" (the BATCHED fused case scan: the whole suite
    advances one epoch per Pallas grid step, per-scenario resets ride a
    VMEM operand — heterogeneous `miner_mask` suites are not supported
    there), or "auto" (the fused MXU path when eligible on this backend
    and `miner_mask is None`, else the XLA vmap).

    `config` may carry batched `[B]` float leaves (a
    :func:`config_grid` grid aligned with the scenario axis — e.g. a
    (case x beta) product suite): the fused path ships them to the
    kernel as per-scenario hyperparameter vectors and the XLA path
    vmaps over them.

    `quarantine=True` folds the resilience layer's per-lane non-finite
    guard into the scan carry (XLA engine only — "auto" then resolves
    to "xla"): a lane whose outputs go NaN/Inf at epoch k is masked to
    zero from that epoch on and recorded in the returned
    `ys["quarantine"]` state (`{bad[B], first_bad_epoch[B],
    tensor_code[B]}` — feed it to
    :func:`..resilience.guards.build_quarantine_report`), while healthy
    lanes stay bit-for-bit identical to an unguarded run. Without it a
    single poisoned lane NaN-contaminates every batch-axis reduction
    downstream.

    `retry_policy` (a :class:`..resilience.retry.RetryPolicy`) arms the
    engine-degradation ladder: classified engine failures on a fused
    rung retry with backoff, then demote toward "xla", logging one
    `event=engine_demoted` record per step (records are log-only here —
    the ys dict stays a pure array pytree).

    `deadline` (a :class:`..resilience.watchdog.Deadline`) arms the
    deadline watchdog around each dispatch: a hang raises a typed
    `EngineStall` (one `event=engine_stalled` record), which the armed
    ladder retries/demotes like any engine failure.

    This wrapper is trace-safe with the default knobs (the sharded
    `shard_map` path calls it inside jit): resilience hooks reduce to
    `is None` checks when unarmed.
    """
    from yuma_simulation_tpu.resilience import faults
    from yuma_simulation_tpu.simulation.planner import plan_dispatch

    # The one dispatch plan (simulation.planner), shared with simulate/
    # simulate_streamed: "auto" prefers the flagship fused batched scan
    # whenever it is eligible (r4 measured a small-shape crossover; r5
    # re-measured it gone after the kernel-closure memoization — warm
    # dispatches at the built-in suite shape are tunnel-RTT-bound and
    # equal within noise, large shapes ~1.5x faster fused), and every
    # fused-rung precondition (no quarantine guard, no per-scenario
    # miner masks, bisect-only consensus) is enforced in ONE place.
    # check_memory=False: this wrapper is re-entered at trace time by
    # the sharded shard_map body — memory is accounted (and preflighted)
    # by whichever entry point placed the arrays.
    plan = plan_dispatch(
        "simulate_batch",
        weights.shape,
        spec,
        config,
        weights.dtype,
        epoch_impl=epoch_impl,
        consensus_impl=consensus_impl,
        save_bonds=save_bonds,
        save_incentives=save_incentives,
        quarantine=quarantine,
        has_miner_mask=miner_mask is not None,
        check_memory=False,
    )
    plan.record()
    epoch_impl = plan.engine

    def _dispatch(rung: str):
        # Profiler step annotation for Perfetto<->ledger alignment.
        # Self-guarded against trace time: the sharded shard_map body
        # re-enters this wrapper while being traced, where annotating
        # would be noise (see telemetry.runctx.dispatch_annotation).
        from yuma_simulation_tpu.telemetry.runctx import dispatch_annotation

        with dispatch_annotation(f"simulate_batch:{rung}"):
            return _dispatch_engine(rung)

    from yuma_simulation_tpu.telemetry.numerics import numerics_enabled

    capture = numerics_enabled()

    def _lane_epochs(fault):
        """`[B]` poison-epoch operand from a per-case fault (-1 =
        healthy lane), shared by the NaN and drift injections."""
        if fault is None:
            return None
        B = weights.shape[0]
        lanes = np.full(B, -1, np.int32)
        if fault.case is None:
            lanes[:] = fault.epoch
        elif 0 <= fault.case < B:
            lanes[fault.case] = fault.epoch
        return jnp.asarray(lanes)

    def _dispatch_engine(rung: str):
        from yuma_simulation_tpu.simulation.planner import (
            FUSED_CASE_RUNGS,
            rung_flags,
        )

        if rung in FUSED_CASE_RUNGS:
            # Reviewed suppression: simulate_batch IS the host-level
            # dispatch wrapper; the sharded shard_map body re-enters it
            # at trace time, where the hook's is-tracing guard no-ops
            # BY DESIGN (sharded dispatches are not drill targets —
            # the fault drills run through the unsharded host path).
            faults.maybe_fail_fused_dispatch()  # jaxlint: disable=JX004
            from yuma_simulation_tpu.simulation.engine import (
                _simulate_case_fused,
            )

            out = _simulate_case_fused(
                weights,
                stakes,
                reset_index,
                reset_epoch,
                config,
                spec,
                save_bonds=save_bonds,
                save_incentives=save_incentives,
                save_consensus=False,
                capture_numerics=capture,
                **rung_flags(rung),
            )
        else:
            # The plan pre-resolved the XLA-rung consensus — both for a
            # direct XLA dispatch and for a demotion off a fused rung
            # (whose checks admit only auto/bisect requests).
            cons = plan.fallback_consensus
            # Reviewed suppression: same host-wrapper re-entry as
            # above — under the sharded trace the hook returns its
            # inert value and no fault arms (drills are unsharded).
            nan_epochs = _lane_epochs(faults.active_nan_fault())  # jaxlint: disable=JX004
            # The drift canary's single-ulp lane flip: armed only
            # inside canary re-executions (faults.canary_scope), so
            # primary dispatches trace the exact production program.
            drift_epochs = _lane_epochs(faults.active_drift_fault())
            out = None
            if nan_epochs is None and drift_epochs is None:
                # The AOT executable-cache seam (simulation.aot):
                # fault-free dispatches resolve the batched program by
                # content — hit = deserialized executable (bitwise the
                # JIT path), miss = JIT as today + publish. Self-guards
                # against the sharded shard_map re-entry (is-tracing
                # check inside) and is a None fast path with no cache.
                from yuma_simulation_tpu.simulation.aot import (
                    dispatch_via_cache,
                )

                batch_kwargs = dict(
                    spec=spec,
                    save_bonds=save_bonds,
                    save_incentives=save_incentives,
                    consensus_impl=cons,
                    guard_nonfinite=quarantine,
                    capture_numerics=capture,
                )
                out = dispatch_via_cache(
                    _simulate_batch_xla,
                    (weights, stakes, reset_index, reset_epoch, config),
                    dict(batch_kwargs, miner_mask=miner_mask),
                    static_names=tuple(batch_kwargs),
                    label=f"simulate_batch:{rung}",
                )
            if out is None:
                out = _simulate_batch_xla(
                    weights,
                    stakes,
                    reset_index,
                    reset_epoch,
                    config,
                    spec,
                    save_bonds=save_bonds,
                    save_incentives=save_incentives,
                    consensus_impl=cons,
                    miner_mask=miner_mask,
                    guard_nonfinite=quarantine,
                    nan_fault_epochs=nan_epochs,
                    capture_numerics=capture,
                    drift_fault_epochs=drift_epochs,
                )
        if retry_policy is not None or deadline is not None:
            out = jax.block_until_ready(out)
        return out

    if retry_policy is None and deadline is None:
        return _dispatch(epoch_impl)
    if retry_policy is None:
        from yuma_simulation_tpu.resilience.watchdog import run_with_deadline

        return run_with_deadline(
            lambda: _dispatch(epoch_impl), deadline, label="simulate_batch"
        )
    from yuma_simulation_tpu.resilience.retry import run_ladder

    ys, _, _ = run_ladder(
        _dispatch, epoch_impl, retry_policy, rungs=plan.ladder,
        label="simulate_batch", deadline=deadline,
    )
    return ys


def sweep_hyperparams(
    scenario: Scenario,
    yuma_version: str,
    configs: YumaConfig,
    *,
    save_bonds: bool = False,
    quarantine: bool = False,
    dtype=jnp.float32,
    initial_state: Optional[dict] = None,
    epoch_offset: int = 0,
):
    """`vmap` one scenario over a batched config pytree (stacked float
    leaves, shared static fields). Build `configs` with :func:`config_grid`.

    `quarantine=True` arms the per-lane non-finite guard exactly as in
    :func:`simulate_batch` — here a lane is one hyperparameter grid
    point, which is the batch axis where NaNs actually originate (a
    pathological `bond_alpha`/`kappa` value poisons its own recurrence
    while every other grid point is fine): the bad lane is masked and
    recorded in `ys["quarantine"]`, the rest of the grid returns
    bit-for-bit the unguarded values.

    `initial_state` / `epoch_offset` (additive — the suffix-resume
    contract, extended to the grid path for the continuous-replay
    controller's incremental windows): resume every lane from ONE
    shared carry (the ``final_state`` of a prior ``return_state=True``
    run over the same config), with the scenario's epochs indexed as
    global epochs ``[offset, offset + E)``. The carry is broadcast
    across lanes, so the prefix-equals-carry precondition only holds
    for lanes whose config matches the carry's producer — a one-point
    grid (the replay controller's window unit), or a grid whose prior
    window genuinely ran all lanes on the shared baseline config.
    Incompatible with `quarantine` (the non-finite guard rides a
    monolithic scan carry)."""
    spec = variant_for_version(yuma_version)
    W = jnp.asarray(scenario.weights, dtype)
    S = jnp.asarray(scenario.stakes, dtype)
    ri = jnp.asarray(
        -1 if scenario.reset_bonds_index is None else scenario.reset_bonds_index,
        jnp.int32,
    )
    re = jnp.asarray(
        -1 if scenario.reset_bonds_epoch is None else scenario.reset_bonds_epoch,
        jnp.int32,
    )
    carry = None
    if initial_state is not None:
        if quarantine:
            raise ValueError(
                "sweep_hyperparams: initial_state does not compose with "
                "quarantine (the guard rides a monolithic scan carry); "
                "pass quarantine=False for suffix-resume grid units"
            )
        from yuma_simulation_tpu.simulation.engine import (
            validate_initial_state,
        )

        _, V, M = np.shape(scenario.weights)
        validate_initial_state(initial_state, spec, V, M)
        carry = {
            k: jnp.asarray(v, dtype) for k, v in initial_state.items()
        }
    from yuma_simulation_tpu.telemetry.numerics import numerics_enabled

    fn = lambda cfg: _simulate_scan(  # noqa: E731
        W,
        S,
        ri,
        re,
        cfg,
        spec,
        save_bonds=save_bonds,
        save_incentives=False,
        save_consensus=False,
        guard_nonfinite=quarantine,
        capture_numerics=numerics_enabled(),
        carry=carry,
        epoch_offset=epoch_offset,
    )
    return jax.vmap(fn)(configs)


def sweep_scaled_fused(
    W: jnp.ndarray,  # [V, M] shared base weights (or [B, V, M] per-point)
    S: jnp.ndarray,  # [V] shared stakes (or [B, V])
    scales: jnp.ndarray,  # [E] per-epoch weight scale
    configs: YumaConfig,  # batched config from config_grid ([B] float leaves)
    yuma_version: str,
    *,
    epoch_impl: str = "auto",
):
    """A hyperparameter grid over the epoch-varying workload as ONE
    dispatch (r3 verdict item 5): the batched fused scan takes the grid's
    `kappa`/`bond_penalty`/`bond_alpha`/... as per-scenario `[B]` vectors
    (a VMEM operand — see `fused_ema_scan`), so the whole `config_grid`
    runs in a single Pallas program instead of one dispatch per point
    (the reference's beta sweep is 4 sequential re-runs of everything,
    reference scripts/charts_table_generator.py:14-16).

    `epoch_impl`: "auto" (the batched exact-MXU fused scan on TPU when
    eligible and the limb split covers V, the VPU scan beyond, else the
    XLA vmap), "fused_scan" / "fused_scan_mxu" (require the batched
    fused path — the two are bitwise-identical; interpret mode off-TPU),
    or "xla" (vmap of the scalar engine over scenarios AND config
    leaves — the parity oracle the fused paths are tested against).

    Returns `(total_dividends [B, V], final_bonds [B, V, M])`.

    Thin wrapper: broadcasts the shared scenario over the grid and
    delegates to :func:`..simulation.engine.simulate_scaled_batch`,
    which owns the batched-config dispatch (one source of truth for the
    auto gate / normalization / error contract).
    """
    from yuma_simulation_tpu.simulation.engine import simulate_scaled_batch

    spec = variant_for_version(yuma_version)
    leaves = jax.tree.leaves(configs)
    B = next((leaf.shape[0] for leaf in leaves if jnp.ndim(leaf) > 0), 1)
    if W.ndim == 2:
        W = jnp.broadcast_to(W, (B,) + W.shape)
        S = jnp.broadcast_to(S, (B,) + S.shape)
    return simulate_scaled_batch(
        W, S, scales, configs, spec, epoch_impl=epoch_impl
    )


def sweepable_config_fields(
    base_simulation: SimulationHyperparameters,
    base_params: YumaParams,
) -> tuple[set, set]:
    """The (simulation, yuma_params) field names a batched config may
    vary: floats only. Static fields (`consensus_precision`,
    `liquid_alpha`, the quantile overrides) select different compiled
    programs and are excluded — ONE exclusion list, shared by the
    cartesian `config_grid` and the foundry's Monte-Carlo sampler."""
    sim_fields = {f for f in vars(base_simulation) if f != "consensus_precision"}
    par_fields = {
        f
        for f in vars(base_params)
        if f not in ("liquid_alpha", "override_consensus_high", "override_consensus_low")
    }
    return sim_fields, par_fields


def build_config_batch(
    points: Sequence[dict],
    base_simulation: Optional[SimulationHyperparameters] = None,
    base_params: Optional[YumaParams] = None,
) -> YumaConfig:
    """Stack per-point float-field overrides into ONE batched
    `YumaConfig` pytree (leaves `[len(points)]` f32). Rejects static/
    unknown field names. The shared back half of :func:`config_grid`
    and `foundry.montecarlo.montecarlo_config_batch`."""
    if not points:
        raise ValueError("config batch needs at least one point")
    base_simulation = base_simulation or SimulationHyperparameters()
    base_params = base_params or YumaParams()
    sim_fields, par_fields = sweepable_config_fields(
        base_simulation, base_params
    )
    for point in points:
        for name in point:
            if name not in sim_fields and name not in par_fields:
                raise ValueError(
                    f"cannot sweep non-float/static field '{name}'"
                )

    def build(point: dict) -> YumaConfig:
        sim = replace(
            base_simulation, **{k: v for k, v in point.items() if k in sim_fields}
        )
        par = replace(
            base_params, **{k: v for k, v in point.items() if k in par_fields}
        )
        return YumaConfig(simulation=sim, yuma_params=par)

    configs = [build(p) for p in points]
    # f32 leaves explicitly: under the x64 parity harness a plain stack
    # of Python floats would produce f64 leaves, which poison the f32
    # engine carries via dtype promotion (framework arrays stay f32 —
    # DESIGN.md "Precision policy").
    return jax.tree.map(
        lambda *leaves: jnp.asarray(np.asarray(leaves, np.float32)), *configs
    )


def config_grid(
    base_simulation: Optional[SimulationHyperparameters] = None,
    base_params: Optional[YumaParams] = None,
    **axes: Sequence[float],
) -> tuple[YumaConfig, list[dict]]:
    """Build a batched `YumaConfig` from a cartesian hyperparameter grid.

    `axes` maps flattened field names (e.g. `kappa`, `bond_alpha`,
    `bond_penalty`) to value lists. Returns the batched config (float
    leaves stacked over the grid's flat order) and the list of grid-point
    dicts in the same order. Static fields (`liquid_alpha`, overrides)
    cannot be swept this way — they select different compiled programs.
    """
    names = list(axes)
    points = [dict(zip(names, combo)) for combo in itertools.product(*axes.values())]
    batched = build_config_batch(points, base_simulation, base_params)
    return batched, points


def total_dividends_batch(
    scenarios: Sequence[Scenario],
    yuma_version: str,
    config: Optional[YumaConfig] = None,
    *,
    dtype=jnp.float32,
) -> np.ndarray:
    """`[B, V]` total dividends for a stacked scenario suite — the batched
    equivalent of summing the reference driver's per-epoch output.

    Same-shaped suites run unpadded; heterogeneous suites are DONOR-
    PACKED via :func:`pack_scenarios` (one tile-aligned batched dispatch
    with per-scenario miner masks — rows then cover the packed
    validator count; entries beyond a scenario's own validator count
    are zero).
    """
    config = config if config is not None else YumaConfig()
    spec = variant_for_version(yuma_version)
    if len({s.weights.shape for s in scenarios}) == 1:
        W, S, ri, re = stack_scenarios(scenarios, dtype)
        ys = simulate_batch(W, S, ri, re, config, spec)
    else:
        W, S, ri, re, mask = pack_scenarios(scenarios, dtype)
        ys = simulate_batch(W, S, ri, re, config, spec, miner_mask=mask)
    return np.asarray(ys["dividends"].sum(axis=1))
