"""Sharded execution: scenario-batch `shard_map` + miner-axis GSPMD.

Replaces — TPU-natively — the distributed layer the reference never had
(SURVEY.md §5: "distributed communication backend: absent"). Two paths:

1. :func:`simulate_batch_sharded` / :func:`montecarlo_total_dividends` —
   the scenario batch is sharded over the mesh's ``data`` axis with
   `jax.shard_map`. Scenarios are independent, so the scan body runs with
   literally zero collectives; results come back as a global array (one
   all-gather / host fetch at the end). This is the cheapest possible
   collective profile for a pod-scale Monte-Carlo sweep.

2. :func:`shard_epoch_over_miners` — for a single subnet whose `[V, M]`
   matrices exceed one chip, the miner axis is sharded with
   `NamedSharding` annotations and XLA/GSPMD inserts the collectives: the
   bisection (the hot loop) is per-miner and stays fully local; only the
   row-normalization sums, the consensus-sum divide and the dividend
   reduction cross shards, each a `[V]`- or scalar-sized psum per epoch.
"""

from __future__ import annotations

import contextlib
import logging
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.epoch import yuma_epoch
from yuma_simulation_tpu.models.variants import VariantSpec, variant_for_version
from yuma_simulation_tpu.ops.consensus import resolve_consensus_impl
from yuma_simulation_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    MeshDegradation,
    surviving_mesh,
)
from yuma_simulation_tpu.scenarios.base import Scenario
from yuma_simulation_tpu.simulation.engine import simulate_constant
from yuma_simulation_tpu.simulation.sweep import simulate_batch, stack_scenarios
from yuma_simulation_tpu.telemetry.metrics import get_registry
from yuma_simulation_tpu.telemetry.runctx import (
    dispatch_annotation,
    span as telemetry_span,
)
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)


def _pad_batch(n: int, shards: int) -> int:
    """Scenarios to add so the batch divides evenly over the data axis."""
    return (-n) % shards


@partial(
    jax.jit,
    static_argnames=(
        "spec", "mesh", "save_bonds", "consensus_impl", "quarantine"
    ),
)
def _sharded_batch_scan(
    weights,  # [B, E, V, M] sharded over B
    stakes,  # [B, E, V]
    reset_index,  # [B]
    reset_epoch,  # [B]
    config: YumaConfig,
    spec: VariantSpec,
    mesh: Mesh,
    save_bonds: bool = False,
    consensus_impl: str = "bisect",
    quarantine: bool = False,
):
    def local_batch(W, S, ri, re):
        # Per-shard slice of the scenario batch; the vmap'd scan comes from
        # the one shared batched entry point so sharded and unsharded paths
        # cannot drift. The quarantine guard is per-lane state, so it
        # shards over the scenario axis like every other output.
        return simulate_batch(
            W,
            S,
            ri,
            re,
            config,
            spec,
            save_bonds=save_bonds,
            save_incentives=False,
            consensus_impl=consensus_impl,
            quarantine=quarantine,
        )

    # check_vma=False: the bisection fori_loop seeds its carry from
    # literals, which the varying-manual-axes checker would force us to
    # pcast shard-by-shard; there is no cross-shard communication here for
    # it to validate.
    return jax.shard_map(
        local_batch,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )(weights, stakes, reset_index, reset_epoch)


def _unpad_outputs(ys: dict, n: int) -> dict:
    """Trim padded lanes and fetch to numpy; a raw per-lane quarantine
    state becomes a host-side :class:`..resilience.guards.QuarantineReport`
    over the un-padded batch. The per-epoch numerics sketches
    (:mod:`..telemetry.numerics`) are a nested `[B, E]`-leaf pytree:
    trimmed leaf-wise — the shard-invariant merge already happened in
    the `shard_map` output gather (every sketch reduction is exact and
    order-independent, so sharded == unsharded bitwise; pinned by
    tests/unit/test_numerics.py)."""
    qstate = ys.pop("quarantine", None)
    numerics = ys.pop("numerics", None)
    out = {k: np.asarray(v)[:n] for k, v in ys.items()}
    if numerics is not None:
        out["numerics"] = jax.tree.map(
            lambda v: np.asarray(v)[:n], numerics
        )
    if qstate is not None:
        from yuma_simulation_tpu.resilience.guards import (
            build_quarantine_report,
        )

        out["quarantine"] = build_quarantine_report(
            {k: np.asarray(v)[:n] for k, v in qstate.items()}
        )
    return out


def simulate_batch_sharded(
    scenarios: Sequence[Scenario],
    yuma_version: str,
    config: Optional[YumaConfig] = None,
    *,
    mesh: Mesh,
    save_bonds: bool = False,
    quarantine: bool = False,
    dtype=jnp.float32,
    elastic: bool = False,
    deadline=None,
):
    """Run a scenario suite sharded over the mesh's data axis.

    Pads the batch to a multiple of the data-axis size with copies of the
    last scenario (dropped from the returned arrays), places the stacked
    inputs with a `NamedSharding` so each host only materializes its
    shard, and returns per-epoch dividends `[B, E, V]` (plus bonds if
    requested) as numpy.

    `quarantine=True` arms the per-lane non-finite guard
    (:mod:`..resilience.guards`) inside every shard — at pod scale this
    is the difference between one poisoned scenario NaN'ing an
    8192-lane study and that scenario being masked with `(case, epoch,
    tensor)` provenance: the returned dict gains a `"quarantine"`
    report (a :class:`..resilience.guards.QuarantineReport` over the
    un-padded batch).

    `elastic=True` arms shrink-and-continue on device loss: a dispatch
    failure attributable to specific devices (a typed
    :class:`..errors.DeviceLossError`, real or fault-injected) rebuilds
    the mesh over the surviving devices (:func:`..mesh.surviving_mesh`),
    re-pads and re-shards the batch for the new data-axis width, and
    re-dispatches — one `event=mesh_degraded` record per shrink, the
    walk returned as `out["mesh_degradations"]` (a tuple of
    :class:`..mesh.MeshDegradation`, empty on the healthy path). The
    last rung is single-device XLA (`simulate_batch`, no `shard_map`) —
    taken when <= 1 device survives or when the failure names no
    surviving-mesh device to drop. Per-lane results are independent of
    the data-axis layout (the shard body is the shared `vmap` engine
    with zero collectives), so a degraded run's lanes are bitwise what
    the full mesh produces. Failures that are NOT device loss (compile
    aborts, OOM, caller errors) propagate unchanged: shrinking the mesh
    cannot fix them, and the retry ladder / caller owns those.

    `deadline` (a :class:`..resilience.watchdog.Deadline`) supervises
    EACH mesh attempt separately — the shrink-and-continue walk runs on
    the caller side of the heartbeat, so a multi-rung recovery gets a
    fresh budget (with retry grace) per rung instead of racing one
    budget for the whole walk. A stall raises a typed `EngineStall` to
    the caller (it is not device loss; shrinking would not fix it).
    """
    config = config if config is not None else YumaConfig()
    spec = variant_for_version(yuma_version)
    n = len(scenarios)
    from yuma_simulation_tpu.resilience import faults
    from yuma_simulation_tpu.resilience.errors import (
        DeviceLossError,
        classify_failure,
    )
    from yuma_simulation_tpu.resilience.watchdog import run_with_deadline

    def dispatch_on(mesh_now: Mesh) -> dict:
        shards = mesh_now.shape[DATA_AXIS]
        pad = _pad_batch(n, shards)
        padded = list(scenarios) + [scenarios[-1]] * pad
        # The dispatch plan per mesh attempt (simulation.planner): each
        # device holds (n + pad) / shards scenario lanes, so a degraded
        # mesh's fatter per-device slice is re-preflighted before the
        # re-dispatch — analytic, pre-compile, typed
        # event=preflight_rejected on reject (a caller error: shrinking
        # further cannot fix it). The plan is recorded here, at the
        # entry point that places the arrays; the shard_map body's
        # trace-time re-entry of simulate_batch plans engine-only.
        from yuma_simulation_tpu.simulation.planner import plan_dispatch

        E_, V_, M_ = np.shape(scenarios[0].weights)
        lanes = (n + pad) // shards
        plan = plan_dispatch(
            f"sharded_batch:{shards}dev",
            (lanes, E_, V_, M_),
            spec,
            config,
            dtype,
            epoch_impl="xla",
            save_bonds=save_bonds,
            quarantine=quarantine,
        )
        plan.record()
        W, S, ri, re = stack_scenarios(padded, dtype)

        sharding = NamedSharding(mesh_now, P(DATA_AXIS))
        W = jax.device_put(W, sharding)
        S = jax.device_put(S, sharding)
        ri = jax.device_put(ri, sharding)
        re = jax.device_put(re, sharding)

        with dispatch_annotation(f"sharded_batch:{shards}dev"):
            return jax.block_until_ready(
                _sharded_batch_scan(
                    W, S, ri, re, config, spec, mesh_now,
                    save_bonds=save_bonds, quarantine=quarantine,
                )
            )

    def dispatch_single_device(device) -> dict:
        W, S, ri, re = stack_scenarios(list(scenarios), dtype)
        # Pin the fallback to a KNOWN SURVIVOR when the degradation walk
        # identified one — JAX's default device may be exactly the one
        # that died. `device=None` (unattributed loss) keeps the
        # default-device behavior: nothing better is known.
        ctx = (
            jax.default_device(device)
            if device is not None
            else contextlib.nullcontext()
        )
        with ctx, dispatch_annotation("sharded_batch:single_device"):
            return jax.block_until_ready(
                simulate_batch(
                    W, S, ri, re, config, spec,
                    save_bonds=save_bonds, save_incentives=False,
                    epoch_impl="xla", quarantine=quarantine,
                )
            )

    degradations: list = []
    mesh_now: Optional[Mesh] = mesh
    fallback_device = None
    while True:
        # Each iteration supervises ONE dispatch on ONE mesh; the
        # shrink logic below runs on the caller side of the watchdog
        # heartbeat, so a legitimate multi-step recovery (cold compile
        # per shard width) gets a fresh budget per rung instead of the
        # whole walk racing a single one. The attempt index is the
        # shrink count, so post-shrink recompiles get the retry grace.
        try:
            if mesh_now is None:
                with telemetry_span("mesh:single_device"):
                    if fallback_device is not None:
                        faults.maybe_lose_device([fallback_device])
                    ys = run_with_deadline(
                        lambda: dispatch_single_device(fallback_device),
                        deadline,
                        label="sharded_batch:single_device",
                        attempt=len(degradations),
                    )
            else:
                with telemetry_span(
                    f"mesh:{int(mesh_now.devices.size)}dev"
                ):
                    # Test-only device-loss drill (inert in production):
                    # fires while the armed plan's lost device is still
                    # part of this mesh, host-level, before any trace.
                    faults.maybe_lose_device(list(mesh_now.devices.flat))
                    # Bind by value: an abandoned (stalled) worker must
                    # not read a mesh the caller has since replaced.
                    ys = run_with_deadline(
                        lambda m=mesh_now: dispatch_on(m),
                        deadline,
                        label="sharded_batch",
                        attempt=len(degradations),
                    )
            break
        except BaseException as exc:  # noqa: BLE001 — classified below
            typed = classify_failure(exc)
            if (
                not elastic
                or mesh_now is None
                or not isinstance(typed, DeviceLossError)
            ):
                raise
            present = {d.id for d in mesh_now.devices.flat}
            lost = tuple(i for i in typed.device_ids if i in present)
            if typed.device_ids and not lost:
                # Names only devices this mesh does not route to: the
                # failure is not attributable here — shrinking cannot
                # help, so propagate rather than loop.
                raise
            survivors = [
                d for d in mesh_now.devices.flat if d.id not in set(lost)
            ]
            if lost and not survivors:
                # Every device of this mesh is gone; there is no rung
                # left to degrade to.
                raise
            new_mesh = surviving_mesh(mesh_now, lost) if lost else None
            if new_mesh is None and lost:
                fallback_device = survivors[0]
            from_n = int(mesh_now.devices.size)
            to_n = int(new_mesh.devices.size) if new_mesh is not None else 1
            record = MeshDegradation(
                from_devices=from_n,
                to_devices=to_n,
                lost_device_ids=lost,
                reason=type(typed).__name__,
            )
            degradations.append(record)
            get_registry().counter(
                "mesh_shrinks", help="elastic mesh degradations"
            ).inc()
            log_event(
                logger,
                "mesh_degraded",
                from_devices=from_n,
                to_devices=to_n,
                lost=",".join(map(str, lost)) if lost else "unattributed",
                reason=record.reason,
            )
            mesh_now = new_mesh

    out = _unpad_outputs(dict(ys), n)
    if elastic:
        out["mesh_degradations"] = tuple(degradations)
    return out


def montecarlo_total_dividends(
    key: jax.Array,
    num_scenarios: int,
    num_epochs: int,
    num_validators: int,
    num_miners: int,
    yuma_version: str,
    config: Optional[YumaConfig] = None,
    *,
    mesh: Mesh,
    base_weights: Optional[jnp.ndarray] = None,
    base_stakes: Optional[jnp.ndarray] = None,
    perturbation: float = 0.05,
    weights_mode: str = "constant",
    consensus_impl: str = "auto",
    epoch_impl: str = "auto",
    dtype=jnp.float32,
) -> np.ndarray:
    """Pod-scale Monte-Carlo: `[num_scenarios, V]` total dividends.

    Weight-perturbation study (BASELINE.json config 5): each scenario's
    weights are `relu(base_weights + eps)` with `eps ~ N(0, perturbation)`
    (the kernel's own row-normalization makes them a distribution; negative
    perturbations truncate at zero), with scenarios generated
    *on-device inside each shard* from a split of ``key`` — no `[B, E, V, M]`
    host array ever exists, so an 8192-scenario x 10k-epoch study is
    bounded by per-chip HBM only. Zero collectives until the final gather.

    `weights_mode` (r4 verdict item 4):
      - "constant" (default): one perturbation per scenario, weights
        constant across its epochs — the hoistable regime.
      - "per_epoch": a FRESH perturbation every epoch (epoch keys folded
        in-scan from the scenario key, `eps_e` generated inside the scan
        step), so the full consensus kernel runs every epoch exactly as
        in the reference's real workload shape — the regime the bench
        headline advertises, at pod scale. Still on-device, still
        HBM-flat in E (no `[E, V, M]` stack exists).

    The scenario batch is padded up to a multiple of the data-axis size
    (extra scenarios simulated and trimmed from the result), matching
    :func:`simulate_batch_sharded`'s contract.

    `consensus_impl`: "auto" (default) picks "sorted" below the documented
    sorted-compile-pathology threshold and "bisect" at or above it
    (:func:`yuma_simulation_tpu.ops.consensus.default_consensus_impl`), so
    a large-subnet study never hits the minutes-to-hours XLA compile of
    the sorted closed form (DESIGN.md); "sorted"/"bisect" force one.

    `epoch_impl`: "hoisted" (the "auto" default for constant weights)
    exploits epoch-constant weights — consensus runs once, the scan
    carries only the bonds recurrence (same values as the full kernel,
    pinned by tests/unit/test_hoisted.py); "xla" forces the full
    per-epoch kernel. `weights_mode="per_epoch"` requires the full
    kernel (nothing is hoistable); "hoisted" there raises.
    """
    config = config if config is not None else YumaConfig()
    spec = variant_for_version(yuma_version)
    consensus_impl = resolve_consensus_impl(
        consensus_impl, num_validators, num_miners
    )
    if weights_mode not in ("constant", "per_epoch"):
        raise ValueError(
            f"unknown weights_mode {weights_mode!r}; "
            "expected 'constant' or 'per_epoch'"
        )
    varying = weights_mode == "per_epoch"
    from yuma_simulation_tpu.simulation.planner import (
        resolve_montecarlo_engine,
    )

    if varying and epoch_impl == "auto" and int(mesh.devices.size) == 1:
        # Single-device per-epoch Monte-Carlo: route through the
        # PLANNED batched driver instead of a one-shard `shard_map` —
        # scenario keys match by construction (both spell them
        # `split(split(key, 1)[0], B)`), the batched XLA rung is
        # bitwise the shard body (shared `_mc_varying_step`, pinned by
        # tests/unit/test_planner.py), and on TPU the planner admits
        # the fused varying rung with device-generated weight slabs —
        # so the public MC API reaches the epoch-tiled fused engine
        # with no host->HBM weight feed and no collective machinery.
        # An explicit epoch_impl="xla" keeps the shard_map tier (the
        # bench continuity line pins that path deliberately).
        return montecarlo_per_epoch_batched(
            key,
            num_scenarios,
            num_epochs,
            num_validators,
            num_miners,
            yuma_version,
            config,
            base_weights=base_weights,
            base_stakes=base_stakes,
            perturbation=perturbation,
            consensus_impl=consensus_impl,
            epoch_impl="auto",
            dtype=dtype,
        )
    epoch_impl = resolve_montecarlo_engine(epoch_impl, varying)
    shards = mesh.shape[DATA_AXIS]
    # Pad-and-trim, the same contract as simulate_batch_sharded (r4
    # verdict weak item 6): extra scenarios are simulated (cheap, they
    # ride the same vmap) and dropped from the returned array.
    padded_n = num_scenarios + _pad_batch(num_scenarios, shards)
    per_shard = padded_n // shards
    if base_weights is None:
        base_weights = jnp.ones((num_validators, num_miners), dtype)
    if base_stakes is None:
        base_stakes = jnp.ones((num_validators,), dtype)
    base_weights = jnp.asarray(base_weights, dtype)
    base_stakes = jnp.asarray(base_stakes, dtype)
    keys = jax.random.split(key, shards)
    run = _montecarlo_varying_run if varying else _montecarlo_run
    out = np.asarray(
        run(
            keys,
            base_weights,
            base_stakes,
            jnp.asarray(perturbation, dtype),
            config,
            num_epochs=num_epochs,
            per_shard=per_shard,
            spec=spec,
            mesh=mesh,
            consensus_impl=consensus_impl,
            hoist_invariant=epoch_impl == "hoisted",
        )
    )
    return out[:num_scenarios]


@partial(
    jax.jit,
    static_argnames=(
        "num_epochs",
        "per_shard",
        "spec",
        "mesh",
        "consensus_impl",
        "hoist_invariant",
    ),
)
def _montecarlo_run(
    keys, base_weights, base_stakes, perturbation, config,
    *, num_epochs: int, per_shard: int, spec: VariantSpec, mesh: Mesh,
    consensus_impl: str = "sorted", hoist_invariant: bool = True,
):
    """Module-level jitted body so repeated Monte-Carlo calls with the same
    shapes/config hit the jit cache instead of re-tracing a fresh closure."""

    def local(shard_keys):
        shard_key = shard_keys[0]

        def one(k):
            eps = perturbation * jax.random.normal(
                k, base_weights.shape, base_weights.dtype
            )
            W = jax.nn.relu(base_weights + eps)
            # Weights are constant across epochs within one scenario, so
            # the hoisted path is the default: consensus once, bonds
            # recurrence scanned (same values as the full per-epoch
            # kernel — pinned by tests/unit/test_hoisted.py).
            total, _ = simulate_constant(
                W,
                base_stakes,
                num_epochs,
                config,
                spec,
                consensus_impl=consensus_impl,
                hoist_invariant=hoist_invariant,
            )
            return total  # [V]

        return jax.vmap(one)(jax.random.split(shard_key, per_shard))

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=P(DATA_AXIS),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )(keys)


def _mc_varying_step(
    k, spec, config, base_weights, base_stakes, perturbation,
    consensus_impl,
):
    """The per-epoch Monte-Carlo scan step for scenario key `k`: a
    fresh perturbation per GLOBAL epoch index (`fold_in(k, epoch)`),
    the full consensus kernel, dividends accumulated in the carry.
    Shared verbatim by the `shard_map` body and the chunked batched
    driver (:func:`montecarlo_per_epoch_batched`), so the two paths are
    bitwise-identical by construction (pinned by
    tests/unit/test_planner.py)."""
    from yuma_simulation_tpu.models.epoch import BondsMode
    from yuma_simulation_tpu.ops.normalize import normalize_weight_rows
    from yuma_simulation_tpu.simulation.carry import TotalsCarry
    from yuma_simulation_tpu.simulation.engine import _dividends_per_1k

    V, M = base_weights.shape
    dtype = base_weights.dtype

    def step(carry, epoch):
        B, W_prev = carry.bonds, carry.w_prev
        eps = perturbation * jax.random.normal(
            jax.random.fold_in(k, epoch), (V, M), dtype
        )
        W = jax.nn.relu(base_weights + eps)
        first = epoch == 0
        kernel_prev = None
        if spec.bonds_mode is BondsMode.EMA_PREV:
            kernel_prev = jnp.where(
                first, normalize_weight_rows(W), W_prev
            )
        res = yuma_epoch(
            W,
            base_stakes,
            B,
            config,
            bonds_mode=spec.bonds_mode,
            W_prev=kernel_prev,
            first_epoch=first,
            consensus_impl=consensus_impl,
        )
        d = _dividends_per_1k(
            res["validator_reward_normalized"],
            base_stakes,
            config,
            dtype,
        )
        W_prev_next = (
            res["weight"] if spec.carries_prev_weights else W_prev
        )
        return (
            TotalsCarry(
                bonds=res[spec.bond_state_key],
                w_prev=W_prev_next,
                consensus=res["server_consensus_weight"],
                acc=carry.acc + d,
            ),
            None,
        )

    return step


def _mc_zero_carry(V: int, M: int, dtype):
    from yuma_simulation_tpu.simulation.carry import TotalsCarry

    return TotalsCarry(
        bonds=jnp.zeros((V, M), dtype),
        w_prev=jnp.zeros((V, M), dtype),
        consensus=jnp.zeros((M,), dtype),
        acc=jnp.zeros((V,), dtype),
    )


@partial(
    jax.jit,
    static_argnames=(
        "num_epochs",
        "per_shard",
        "spec",
        "mesh",
        "consensus_impl",
        "hoist_invariant",
    ),
)
def _montecarlo_varying_run(
    keys, base_weights, base_stakes, perturbation, config,
    *, num_epochs: int, per_shard: int, spec: VariantSpec, mesh: Mesh,
    consensus_impl: str = "bisect", hoist_invariant: bool = False,
):
    """EPOCH-VARYING Monte-Carlo shard body: every epoch of every
    scenario draws a fresh perturbation (`fold_in(scenario_key, epoch)`
    inside the scan step), so the FULL consensus kernel executes per
    epoch — the reference's real workload shape (simulation_utils.py:
    44-46) at pod scale, with no `[E, V, M]` stack ever materialized.
    The scan carry mirrors the engine's `(B, W_prev, C_prev)` state
    machine (resets don't apply — synthetic scenarios carry no reset
    metadata, as in the constant-weights path)."""
    del hoist_invariant  # nothing is hoistable with per-epoch weights
    from jax import lax

    V, M = base_weights.shape
    dtype = base_weights.dtype

    def local(shard_keys):
        shard_key = shard_keys[0]

        def one(k):
            step = _mc_varying_step(
                k, spec, config, base_weights, base_stakes, perturbation,
                consensus_impl,
            )
            final, _ = lax.scan(
                step,
                _mc_zero_carry(V, M, dtype),
                jnp.arange(num_epochs, dtype=jnp.int32),
            )
            return final.acc  # [V]

        return jax.vmap(one)(jax.random.split(shard_key, per_shard))

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=P(DATA_AXIS),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )(keys)


@partial(
    jax.jit,
    static_argnames=("chunk_epochs", "spec", "consensus_impl"),
    donate_argnames=("carry",),
)
def _montecarlo_varying_chunk(
    keys, carry, epoch_lo, base_weights, base_stakes, perturbation,
    config, *, chunk_epochs: int, spec: VariantSpec,
    consensus_impl: str = "bisect",
):
    """One `[B]`-batched chunk of the per-epoch Monte-Carlo on the XLA
    engine: each scenario advances `chunk_epochs` GLOBAL epochs from
    `epoch_lo` with the full `TotalsCarry` state threaded (and donated)
    between dispatches — the same step function as the monolithic
    shard body, so chunked == monolithic bitwise."""
    from jax import lax

    def one(k, c):
        step = _mc_varying_step(
            k, spec, config, base_weights, base_stakes, perturbation,
            consensus_impl,
        )
        final, _ = lax.scan(
            step,
            c,
            jnp.asarray(epoch_lo, jnp.int32)
            + jnp.arange(chunk_epochs, dtype=jnp.int32),
        )
        return final

    return jax.vmap(one, in_axes=(0, 0))(keys, carry)


@jax.jit
def _mc_epoch_sum(totals, dividends):
    """`totals + dividends summed over the epoch axis`, accumulated
    STRICTLY in epoch order (a scan, not `jnp.sum` — whose reduction
    order XLA may tree up differently per chunk length): the planner's
    `chunk_epochs` cap must never change results, so the chunked total
    is bitwise the monolithic one on the same engine."""
    from jax import lax

    return lax.scan(
        lambda t, d: (t + d, None), totals, dividends.swapaxes(0, 1)
    )[0]


@partial(jax.jit, static_argnames=("chunk_epochs",))
def _montecarlo_weight_slab(
    keys, epoch_lo, base_weights, perturbation, *, chunk_epochs: int
):
    """`[B, CH, V, M]` genuinely-fresh per-epoch weights for the fused
    batched scan — the SAME draws as the in-scan generation
    (`fold_in(k, global_epoch)`), materialized one slab at a time so
    the single-Pallas-program scan can stream them from HBM."""

    def one(k):
        def per_epoch(e):
            eps = perturbation * jax.random.normal(
                jax.random.fold_in(k, e),
                base_weights.shape,
                base_weights.dtype,
            )
            return jax.nn.relu(base_weights + eps)

        return jax.vmap(per_epoch)(
            jnp.asarray(epoch_lo, jnp.int32)
            + jnp.arange(chunk_epochs, dtype=jnp.int32)
        )

    return jax.vmap(one)(keys)


def montecarlo_per_epoch_batched(
    key: jax.Array,
    num_scenarios: int,
    num_epochs: int,
    num_validators: int,
    num_miners: int,
    yuma_version: str,
    config: Optional[YumaConfig] = None,
    *,
    base_weights: Optional[jnp.ndarray] = None,
    base_stakes: Optional[jnp.ndarray] = None,
    perturbation: float = 0.05,
    consensus_impl: str = "auto",
    epoch_impl: str = "auto",
    chunk_epochs: Optional[int] = None,
    dtype=jnp.float32,
) -> np.ndarray:
    """The per-epoch-weights Monte-Carlo as ONE batched engine ride —
    the donor-packed answer to BENCH's `montecarlo_per_epoch_weights`
    gap (6.9k vs the 62k fused-scan line, ROADMAP item 5): instead of
    `B` scenarios each scanning the unfused kernel, the whole batch
    advances together through the planner-chosen engine.

    Engine rungs (``epoch_impl``, planned by
    :func:`..simulation.planner.plan_dispatch` on the `[B, CH, V, M]`
    slab shape):

    - ``fused_varying`` / ``fused_varying_mxu`` (what "auto" picks on
      TPU when the epoch-tiled scan's divisor tile reaches 2 — the
      small-`V x M` Monte-Carlo shape is exactly the workload the tile
      exists for) and ``fused_scan`` / ``fused_scan_mxu`` (the
      per-epoch fused fallback): each chunk's fresh weights are
      generated on device as one `[B, CH, V, M]` slab
      (:func:`_montecarlo_weight_slab` — the SAME `fold_in(key,
      global_epoch)` draws as the in-scan generation) and streamed
      through the batched single-Pallas-program scan with the bond
      carry threaded (donated) between chunks; varying-rung slab
      lengths are rounded to tile multiples (the epoch-tiled kernel
      pads no epochs). Only one slab plus the in-flight generation is
      resident — HBM stays flat in E.
    - ``xla`` (the CPU/ineligible fallback and the parity oracle): the
      batched in-scan generation with the `TotalsCarry` threaded per
      chunk — BITWISE the monolithic
      :func:`montecarlo_total_dividends` shard body (same step
      function, same keys; pinned by tests/unit/test_planner.py).

    `chunk_epochs` (default: the plan's memory-plan slab cap, or the
    whole run when capacity is unknown) trades dispatch count against
    slab residency. Chunk-length invariance is bitwise on the XLA and
    per-epoch fused rungs; on the epoch-tiled varying rungs different
    chunk lengths compile different programs, so totals agree to
    reduction-order rounding (the epoch-ordered accumulation keeps the
    composition exact per program — tests/unit/test_varying_scan.py). Keys match ``montecarlo_total_dividends(...,
    mesh=<1 device>)``: scenario keys are
    ``split(split(key, 1)[0], B)``.

    Returns `[num_scenarios, V]` total dividends as numpy.
    """
    from yuma_simulation_tpu.simulation.planner import plan_dispatch

    config = config if config is not None else YumaConfig()
    spec = variant_for_version(yuma_version)
    V, M = num_validators, num_miners
    if base_weights is None:
        base_weights = jnp.ones((V, M), dtype)
    if base_stakes is None:
        base_stakes = jnp.ones((V,), dtype)
    base_weights = jnp.asarray(base_weights, dtype)
    base_stakes = jnp.asarray(base_stakes, dtype)
    B = int(num_scenarios)
    # The RAW consensus request goes to the planner so the contract
    # matches every other entry point: auto+sorted falls back to the
    # XLA rung, an explicit fused rung with "sorted" raises, and
    # `plan.fallback_consensus` is the shape-gated resolution the XLA
    # rung uses (same as montecarlo_total_dividends' own resolve).
    plan = plan_dispatch(
        f"montecarlo_batched:{yuma_version}",
        (B, num_epochs, V, M),
        spec,
        config,
        dtype,
        epoch_impl=epoch_impl,
        consensus_impl=consensus_impl,
        streaming=True,
    )
    plan.record()
    from yuma_simulation_tpu.simulation.planner import (
        FUSED_CASE_RUNGS,
        rung_flags,
    )

    fused = plan.engine in FUSED_CASE_RUNGS
    varying_rung = plan.engine in ("fused_varying", "fused_varying_mxu")
    if chunk_epochs is None:
        # Only the fused rung materializes a slab; the XLA rung
        # generates in-scan (HBM flat in E) and defaults to one
        # dispatch over the whole run.
        chunk_epochs = (
            plan.memory.chunk_epochs or num_epochs
        ) if fused else num_epochs
    chunk_epochs = max(1, min(int(chunk_epochs), num_epochs))
    if varying_rung:
        from yuma_simulation_tpu.ops.pallas_epoch import (
            VARYING_EPOCH_TILE_MAX,
        )

        if chunk_epochs > VARYING_EPOCH_TILE_MAX:
            # The epoch-tiled rung pads no epochs: round the slab
            # length down to a tile multiple so every full chunk runs
            # the deepest tile (the remainder chunk picks its own
            # divisor tile).
            chunk_epochs -= chunk_epochs % VARYING_EPOCH_TILE_MAX
    keys = jax.random.split(jax.random.split(key, 1)[0], B)
    perturbation = jnp.asarray(perturbation, dtype)

    if fused:
        from yuma_simulation_tpu.simulation.engine import (
            _simulate_case_fused_streamed,
        )

        ri = jnp.asarray(-1, jnp.int32)
        carry = {
            "bonds": jnp.zeros((B, V, M), dtype),
            "consensus": jnp.zeros((B, M), dtype),
        }
        if spec.carries_prev_weights:
            carry["w_prev"] = jnp.zeros((B, V, M), dtype)
        S_slab = jnp.broadcast_to(
            base_stakes, (B, chunk_epochs, V)
        )
        totals = jnp.zeros((B, V), dtype)
        nxt = _montecarlo_weight_slab(
            keys, 0, base_weights, perturbation, chunk_epochs=chunk_epochs
        )
        for lo in range(0, num_epochs, chunk_epochs):
            hi = min(lo + chunk_epochs, num_epochs)
            W_slab = nxt
            if hi - lo < chunk_epochs:
                W_slab = W_slab[:, : hi - lo]
                S_slab = S_slab[:, : hi - lo]
            ys, carry = _simulate_case_fused_streamed(
                W_slab,
                S_slab,
                ri,
                ri,
                config,
                spec,
                save_bonds=False,
                save_incentives=False,
                carry=carry,
                epoch_offset=lo,
                return_carry=True,
                **rung_flags(plan.engine),
            )
            if hi < num_epochs:
                # Double-buffer: next slab's generation is queued while
                # the current chunk's scan runs.
                nxt = _montecarlo_weight_slab(
                    keys, hi, base_weights, perturbation,
                    chunk_epochs=chunk_epochs,
                )
            totals = _mc_epoch_sum(totals, ys["dividends"])
        return np.asarray(totals)

    carry = jax.vmap(lambda _: _mc_zero_carry(V, M, dtype))(keys)
    for lo in range(0, num_epochs, chunk_epochs):
        hi = min(lo + chunk_epochs, num_epochs)
        carry = _montecarlo_varying_chunk(
            keys,
            carry,
            lo,
            base_weights,
            base_stakes,
            perturbation,
            config,
            chunk_epochs=hi - lo,
            spec=spec,
            consensus_impl=plan.fallback_consensus,
        )
    return np.asarray(carry.acc)


def shard_epoch_over_miners(
    W: jnp.ndarray,
    S: jnp.ndarray,
    B_old: Optional[jnp.ndarray],
    config: YumaConfig,
    *,
    mesh: Mesh,
    bonds_mode,
    consensus_impl: str = "bisect",
) -> dict:
    """One consensus epoch with the miner axis sharded over ``model``.

    The "sequence-parallel" analogue for this domain (SURVEY.md §5): when
    `V x M` outgrows a chip, `W`, `B` and all `[M]`-vectors shard on the
    miner axis. Sharding is expressed with `NamedSharding` constraints and
    the collectives are left to GSPMD — the bisection support sums reduce
    over the *validator* axis (replicated), so the hot loop is entirely
    local; cross-shard traffic is a handful of scalar/row reductions.
    """
    vm = NamedSharding(mesh, P(None, MODEL_AXIS))
    m = NamedSharding(mesh, P(MODEL_AXIS))
    rep = NamedSharding(mesh, P())

    W = jax.device_put(jnp.asarray(W), vm)
    S = jax.device_put(jnp.asarray(S), rep)
    if B_old is not None:
        B_old = jax.device_put(jnp.asarray(B_old), vm)

    @partial(jax.jit, static_argnames=("bonds_mode", "consensus_impl"))
    def step(W, S, B_old, config, bonds_mode, consensus_impl):
        out = yuma_epoch(
            W,
            S,
            B_old,
            config,
            bonds_mode=bonds_mode,
            consensus_impl=consensus_impl,
        )
        # Pin the layouts of the large outputs so downstream epochs keep
        # the miner axis sharded instead of gathering.
        for k in ("weight", "consensus_clipped_weight"):
            out[k] = jax.lax.with_sharding_constraint(out[k], vm)
        for k in ("server_consensus_weight", "server_incentive"):
            out[k] = jax.lax.with_sharding_constraint(out[k], m)
        for k in ("validator_bond", "validator_ema_bond", "validator_bonds"):
            if k in out:
                out[k] = jax.lax.with_sharding_constraint(out[k], vm)
        return out

    return step(W, S, B_old, config, bonds_mode, consensus_impl)
