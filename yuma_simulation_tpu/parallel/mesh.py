"""Device-mesh construction for single-chip, pod (ICI) and multi-slice (DCN).

The reference has no distributed backend at all (SURVEY.md §5); the
TPU-native equivalent is a `jax.sharding.Mesh` whose axes the rest of the
framework shards over:

- ``"data"``  — the scenario / Monte-Carlo batch axis (no per-epoch traffic);
- ``"model"`` — the miner axis of the `[V, M]` weight/bond matrices, for
  subnets too large for one chip's HBM.

Meshes are plain data; all collective placement is decided by the sharding
annotations in :mod:`yuma_simulation_tpu.parallel.sharded`.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    data: int = -1,
    model: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a `(data, model)` mesh over the available devices.

    ``data=-1`` absorbs whatever is left after ``model`` (the common case:
    shard scenarios over every chip). On a real TPU slice
    `mesh_utils.create_device_mesh` picks an ICI-contiguous layout; on CPU
    test meshes (``--xla_force_host_platform_device_count=N``) it reduces
    to a reshape.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data == -1:
        if n % model:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} devices")
    try:
        dev_array = mesh_utils.create_device_mesh(
            (data, model), devices=devices
        )
    except Exception:  # non-TPU platforms without topology info
        dev_array = np.asarray(devices).reshape(data, model)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


@dataclasses.dataclass(frozen=True)
class MeshDegradation:
    """One elastic shrink of a sweep's mesh (also logged as
    `event=mesh_degraded`): which devices were dropped, what the mesh
    shrank from and to, and why.

    The same shape describes a shrink at EVERY level of the elastic
    hierarchy — the fabric layer reuses it verbatim for host loss
    (:mod:`yuma_simulation_tpu.fabric.health` aliases it as
    ``FleetDegradation``), where the "devices" are fleet hosts."""

    from_devices: int
    to_devices: int
    lost_device_ids: tuple
    reason: str


def surviving_members(
    members: Sequence, lost_ids: Sequence, *, key=None
) -> list:
    """The survivor filter shared by every level of elastic degradation:
    drop `lost_ids` from `members`, identity taken from ``member.id``
    when present (jax devices) else the member itself (fleet host ids).
    :func:`surviving_mesh` applies it to a mesh's devices; the fleet
    fabric applies it one level up to the host roster — same semantics,
    one implementation (ROADMAP item 4)."""
    if key is None:
        key = lambda m: getattr(m, "id", m)  # noqa: E731
    lost = set(lost_ids)
    return [m for m in members if key(m) not in lost]


def surviving_mesh(
    mesh: Mesh, lost_device_ids: Sequence[int]
) -> Optional[Mesh]:
    """Rebuild `mesh` over its surviving devices after losing
    `lost_device_ids` — the shrink-and-continue step of elastic
    degradation (Pathways-style: a sweep outlives a device, it does not
    die with it).

    The ``model`` axis width is preserved when the survivor count still
    divides by it (miner-sharded programs keep their collective
    geometry); otherwise it collapses to 1 — a scenario-batch sweep has
    no cross-shard traffic, so any data-axis width is valid. Returns
    None when zero devices survive, or when exactly one does: one device
    cannot host a multi-axis mesh usefully, and the caller's last rung
    (single-device XLA, no `shard_map`) is strictly simpler than a 1x1
    mesh. One `event=mesh_degraded` record is emitted per rebuild by the
    elastic driver, not here — the driver knows the dispatch context.
    """
    survivors = surviving_members(list(mesh.devices.flat), lost_device_ids)
    if len(survivors) <= 1:
        return None
    model = mesh.shape.get(MODEL_AXIS, 1)
    if model > 1 and len(survivors) % model:
        model = 1
    return make_mesh(data=-1, model=model, devices=survivors)


def make_hybrid_mesh(
    data_per_slice: int = -1, model: int = 1
) -> Mesh:
    """Multi-slice mesh: scenario batch over DCN x ICI, miner axis on ICI.

    Uses `mesh_utils.create_hybrid_device_mesh` so the ``model`` axis (which
    carries the per-epoch collectives) is always intra-slice (ICI) and only
    the collective-free ``data`` axis spans DCN. Falls back to
    :func:`make_mesh` in single-slice / CPU environments.
    """
    devices = jax.devices()
    num_slices = max(
        (getattr(d, "slice_index", 0) or 0 for d in devices), default=0
    ) + 1
    if num_slices <= 1:
        return make_mesh(data_per_slice, model)
    per_slice = len(devices) // num_slices
    if per_slice % model:
        raise ValueError(
            f"{per_slice} devices/slice not divisible by model={model}"
        )
    if data_per_slice == -1:
        data_per_slice = per_slice // model
    if data_per_slice * model != per_slice:
        raise ValueError(
            f"per-slice mesh {data_per_slice}x{model} != {per_slice} devices"
        )
    dev_array = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(data_per_slice, model),
        dcn_mesh_shape=(num_slices, 1),
        devices=devices,
    )
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    initialization_timeout: Optional[int] = None,
) -> None:
    """Join a multi-host JAX run (the NCCL/MPI-init analogue).

    A no-op when already initialized; call it *before* anything touches the
    backend (any `jax.devices()` / array op initializes local-only XLA and
    makes later distributed init fail). Arguments default to the standard
    JAX env-var autodetection (GKE / Cloud TPU metadata).

    Failure semantics: with ALL arguments defaulted (autodetection), a
    failed init degrades to a single-process run with a debug log — the
    laptop/CI case. With an EXPLICIT coordinator the caller has declared
    the run distributed, so a peer that never joins (crashed before the
    barrier, wrong address, ...) raises within `initialization_timeout`
    seconds instead of silently simulating 1/N of the workload as if it
    were the whole job (failure-detection contract, pinned by
    tests/unit/test_distributed_multiprocess.py).
    """
    if jax.distributed.is_initialized():
        return
    explicit = coordinator_address is not None
    kwargs = {}
    if initialization_timeout is not None:
        kwargs["initialization_timeout"] = initialization_timeout
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
        logger.info(
            "distributed: process %d/%d, %d local / %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.local_device_count(),
            jax.device_count(),
        )
    except (RuntimeError, ValueError) as e:
        if explicit:
            # Typed, logged failure instead of the raw backend error: a
            # peer that never joined within initialization_timeout is an
            # operator-actionable event (re-launch the job), and the one
            # structured record makes it greppable alongside every other
            # recovery action (README "Failure semantics & recovery").
            from yuma_simulation_tpu.resilience.errors import (
                DistributedInitError,
            )

            log_event(
                logger,
                "distributed_init_failed",
                coordinator=coordinator_address,
                process=process_id if process_id is not None else "",
                num_processes=(
                    num_processes if num_processes is not None else ""
                ),
                timeout_s=(
                    initialization_timeout
                    if initialization_timeout is not None
                    else ""
                ),
                error=type(e).__name__,
            )
            raise DistributedInitError(
                f"distributed join failed for explicit coordinator "
                f"{coordinator_address} (process {process_id}/"
                f"{num_processes}); refusing to degrade to a "
                "single-process run"
            ) from e
        logger.debug("single-process run (distributed init skipped: %s)", e)
