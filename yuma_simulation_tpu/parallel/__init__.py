"""Pod scale-out: device meshes, sharded sweeps, Monte-Carlo at scale.

The reference is single-process/single-thread CPU (SURVEY.md §2: no
distributed code exists there); this package is the TPU-native scaling
layer it lacks. Two orthogonal axes:

- **Scenario batch ("data")** — embarrassingly parallel; `shard_map` over
  the mesh's data axis with zero collectives inside the epoch scan and one
  gather at the end (:func:`simulate_batch_sharded`,
  :func:`montecarlo_total_dividends`).
- **Miner axis ("model")** — when a subnet's `[V, M]` matrices outgrow one
  chip, shard the miner dimension with GSPMD sharding annotations and let
  XLA insert the (few, tiny) collectives: row-sum psums for weight
  normalization, a scalar psum for the consensus quantization divide, and
  an `[M]`-vector gather for liquid-alpha quantiles
  (:func:`shard_epoch_over_miners`).

Multi-host (DCN) meshes put the scenario axis on DCN and the miner axis on
ICI (:func:`make_hybrid_mesh`), so all per-epoch traffic rides ICI.
"""

from yuma_simulation_tpu.parallel.mesh import (  # noqa: F401
    MeshDegradation,
    initialize_distributed,
    make_hybrid_mesh,
    make_mesh,
    surviving_members,
    surviving_mesh,
)
from yuma_simulation_tpu.parallel.sharded import (  # noqa: F401
    montecarlo_total_dividends,
    shard_epoch_over_miners,
    simulate_batch_sharded,
)
