"""Frozen public API surface (ApiVer), version 1.

Mirrors the reference's contract (reference README.md:10-18): everything
under `v1` is stable; internals under the other subpackages may change
freely. Import the api module explicitly:

    from yuma_simulation_tpu.v1 import api
"""
