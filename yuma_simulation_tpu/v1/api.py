"""Public API v1: chart tables, dividend tables, the simulation driver.

Drop-in surface for the reference's `yuma_simulation.v1.api`
(reference v1/api.py:24-132) with the same signatures and HTML/CSV
output shape, plus two promotions the reference kept internal
(SURVEY.md §1): `generate_total_dividends_table` and `run_simulation`.

One structural fix over the reference: the reference re-runs every
simulation once per chart type (4-5x redundant compute, reference
v1/api.py:59-67 — flagged in SURVEY.md §2 as "fix, not replicate");
here each (case, version) pair is simulated exactly once and its outputs
are reused across all chart rows.
"""

from __future__ import annotations

import pandas as pd
from IPython.display import HTML

from yuma_simulation_tpu.models.config import (  # noqa: F401  (public re-exports)
    SimulationHyperparameters,
    YumaConfig,
    YumaParams,
    YumaSimulationNames,
)
from yuma_simulation_tpu.models.variants import (
    variant_for_version as _variant_for_version,
)
from yuma_simulation_tpu.reporting.charts import (
    plot_bonds as _plot_bonds,
    plot_dividends as _plot_dividends,
    plot_incentives as _plot_incentives,
    plot_validator_server_weights as _plot_validator_server_weights,
)
from yuma_simulation_tpu.reporting.tables import (
    generate_draggable_html_table as _generate_draggable_html_table,
    generate_ipynb_table as _generate_ipynb_table,
)
from yuma_simulation_tpu.reporting.tables import (  # noqa: F401  (promoted)
    generate_total_dividends_table,
)
from yuma_simulation_tpu.foundry import (  # noqa: F401  (promoted, 0.16.0)
    cartel_scenario,
    compile_spec,
    load_metagraph_snapshot,
    stake_churn_scenario,
    takeover_scenario,
    weight_copier_scenario,
)
from yuma_simulation_tpu.replay import (  # noqa: F401  (promoted, 0.18.0)
    SnapshotArchive,
    StateCache,
    WhatIfSpec,
    sweep_trailing_window,
)
from yuma_simulation_tpu.scenarios.base import Scenario
from yuma_simulation_tpu.serve.server import (  # noqa: F401  (promoted)
    SimulationClient,
)
from yuma_simulation_tpu.simulation.engine import run_simulation  # noqa: F401
from yuma_simulation_tpu.simulation.sweep import (
    pad_scenarios as _pad_scenarios,
    simulate_batch as _simulate_batch,
)

#: The frozen ApiVer surface (reference README.md:15-18): exactly these
#: names are public; everything else in this module is an implementation
#: detail that may change without notice. 0.12.0 grows it ADDITIVELY
#: with the serving tier's entry point + client; 0.16.0 with the
#: scenario foundry — the DSL compiler, metagraph snapshot ingestion,
#: and the four adversarial family builders; 0.18.0 with the
#: chain-replay service — the snapshot-timeline archive, the epoch-
#: state cache, what-if specs, and the trailing-window fleet sweep
#: (MIGRATION.md).
__all__ = [
    "HTML",
    "Scenario",
    "SimulationClient",
    "SimulationHyperparameters",
    "SnapshotArchive",
    "StateCache",
    "WhatIfSpec",
    "YumaConfig",
    "YumaParams",
    "YumaSimulationNames",
    "cartel_scenario",
    "compile_spec",
    "generate_chart_table",
    "generate_total_dividends_table",
    "load_metagraph_snapshot",
    "run_simulation",
    "serve",
    "stake_churn_scenario",
    "sweep_trailing_window",
    "takeover_scenario",
    "weight_copier_scenario",
]


def serve(
    host: str = "127.0.0.1",
    port: int = 8080,
    *,
    background: bool = False,
    **knobs,
):
    """Start the warm-engine simulation service (README "Serving"):
    `simulate`/`sweep`/chart-table endpoints with admission control,
    per-tenant quotas, shape-bucket coalescing and graceful degradation,
    plus `/metrics` and `/healthz`.

    Blocking by default (the CLI behavior: serve until interrupted);
    `background=True` returns the started
    :class:`..serve.server.SimulationServer` — call ``.close()`` for a
    graceful drain. `knobs` are :class:`..serve.service.ServeConfig`
    fields (``queue_limit``, ``coalesce_window_seconds``,
    ``tenant_rate``, ``bundle_dir``, ...)."""
    from yuma_simulation_tpu.serve.server import SimulationServer
    from yuma_simulation_tpu.serve.service import ServeConfig

    server = SimulationServer(ServeConfig(**knobs), host=host, port=port)
    if background:
        return server.start()
    server.serve_forever()
    return server

#: Chart rows rendered per case; cases with `plot_incentives` (Cases 10
#: and 11 of the built-in suite — the reference keys this off positional
#: indices 9/10, reference v1/api.py:42-45) add the incentives row.
_CHART_TYPES = ["weights", "dividends", "bonds", "normalized_bonds"]


def _decorated_case_name(
    case: Scenario, yuma_version: str, config: YumaConfig
) -> str:
    """Chart title: case + version, with the beta / alpha-range suffixes
    the reference appends for the EMA and liquid-alpha-4 families
    (reference v1/api.py:52-57)."""
    names = YumaSimulationNames()
    full = f"{case.name} - {yuma_version}"
    if yuma_version in (names.YUMA, names.YUMA_LIQUID, names.YUMA2):
        return f"{full} - beta={config.bond_penalty}"
    if yuma_version == names.YUMA4_LIQUID:
        return f"{full} [{config.alpha_low}, {config.alpha_high}]"
    return full


def _simulate_suite(
    cases: list[Scenario],
    yuma_versions: list[tuple[str, YumaParams]],
    yuma_hyperparameters: SimulationHyperparameters,
    supervised: bool = False,
) -> dict:
    """ONE batched dispatch per version over the (padded) case suite,
    un-padded back to per-case `run_simulation`-shaped outputs.

    The per-(case, version) `run_simulation` loop costs a device
    round-trip each — 126 dispatches for the canonical 14x9 sweep, which
    on a remote-tunnel TPU runtime (~0.1 s/dispatch) dominates the whole
    chart build (~21 s measured warm). Batching the suite through
    `simulate_batch` (the same vmap'd engine the golden-pinned
    total-dividends table uses, heterogeneous shapes handled by
    `pad_scenarios`' inert padding) reduces it to one dispatch per
    version. Returns `{(case_idx, version): (config, (dividends_dict,
    bonds_per_epoch, incentives_per_epoch))}`.

    Engine note (DESIGN.md "Precision policy"): a same-shaped suite
    (the built-in 14 cases included) is stacked unpadded and routed
    through `simulate_batch`'s `epoch_impl="auto"` — on TPU that is the
    fused Pallas case scan, the same flagship engine `run_simulation`
    defaults to, so the production chart/CSV artifacts execute the
    flagship kernels (r4 verdict item 6; the r4 small-shape crossover
    no longer reproduces — see simulate_batch's auto note). A
    heterogeneous suite is padded with per-scenario miner masks, which
    the batched fused scan does not support, and takes the XLA vmap.
    Both engines pass the golden surface independently, and since the
    canonical fixed-point support test (r4) they agree BITWISE on
    consensus for every input — including adversarial knife-edge
    `support == kappa` ties (CROSS_ENGINE.json: 0/90 mismatch runs);
    residual cross-engine output differences are downstream f32
    arithmetic-order effects (~3e-8 measured over the built-in suite's
    dividends).
    """
    import numpy as np

    if not cases:
        # pad_scenarios rejects an empty suite; the chart table renders
        # empty, as the old per-case loop did.
        return {}
    if len({c.weights.shape for c in cases}) == 1:
        from yuma_simulation_tpu.simulation.sweep import stack_scenarios

        W, S, ri, re = stack_scenarios(cases)
        mask = None
    else:
        W, S, ri, re, mask = _pad_scenarios(cases)
    # The production chart/CSV build rides the engine-degradation
    # ladder: a fused-engine compile failure or VMEM exhaustion retries
    # and demotes to the XLA scan (one structured log record per
    # demotion) instead of aborting the whole artifact build. On the
    # happy path this is a single no-op predicate check.
    from yuma_simulation_tpu.resilience.retry import default_retry_policy

    # `supervised=True` additionally arms the deadline watchdog: a HUNG
    # compile/dispatch (which raises nothing on its own) is killed at
    # the default budget and retried/demoted through the same ladder.
    deadline = None
    if supervised:
        from yuma_simulation_tpu.resilience.supervisor import default_deadline

        deadline = default_deadline()
    # Run-scoped telemetry: the whole suite build shares one run_id
    # (joining an operator-opened CLI RunContext when present), and each
    # version's batched dispatch is one span — every engine-demotion /
    # stall record emitted below carries the run/span identity.
    from yuma_simulation_tpu.telemetry import ensure_run, span

    out = {}
    with ensure_run(), span("chart_suite", versions=len(yuma_versions)):
        for yuma_version, yuma_params in yuma_versions:
            config = YumaConfig(
                simulation=yuma_hyperparameters, yuma_params=yuma_params
            )
            spec = _variant_for_version(yuma_version)
            with span(f"version:{yuma_version}"):
                ys = _simulate_batch(
                    W, S, ri, re, config, spec,
                    save_bonds=True, save_incentives=True, miner_mask=mask,
                    retry_policy=default_retry_policy(), deadline=deadline,
                )
            div = np.asarray(ys["dividends"])  # [B, Ep, Vp]
            bonds = np.asarray(ys["bonds"])  # [B, Ep, Vp, Mp]
            inc = np.asarray(ys["incentives"])  # [B, Ep, Mp]
            for i, case in enumerate(cases):
                E, V, M = case.weights.shape
                dividends = {
                    validator: [float(x) for x in div[i, :E, j]]
                    for j, validator in enumerate(case.validators)
                }
                out[(i, yuma_version)] = (
                    config,
                    (
                        dividends,
                        list(bonds[i, :E, :V, :M]),
                        list(inc[i, :E, :M]),
                    ),
                )
    return out


def generate_chart_table(
    cases: list[Scenario],
    yuma_versions: list[tuple[str, YumaParams]],
    yuma_hyperparameters: SimulationHyperparameters,
    draggable_table: bool = False,
    supervised: bool = False,
) -> "HTML":
    """Simulate every case x version and assemble the chart grid
    (rows = chart types per case, columns = versions) as an
    `IPython.display.HTML` (reference v1/api.py:24-132).

    `supervised=True` (new; off by default) runs every simulation under
    the full supervision tier — deadline watchdog + engine-degradation
    ladder — so an unattended artifact build survives hung compiles as
    well as raising engine failures (README "Supervised sweeps")."""
    table_data: dict[str, list[str]] = {v: [] for v, _ in yuma_versions}
    case_row_ranges: list[tuple[int, int, int]] = []
    row = 0

    # One simulation per (case, version) — batched into one dispatch per
    # version across the whole suite.
    per_pair = _simulate_suite(
        cases, yuma_versions, yuma_hyperparameters, supervised=supervised
    )

    for idx, case in enumerate(cases):
        chart_types = list(_CHART_TYPES)
        if getattr(case, "plot_incentives", False):
            chart_types.append("incentives")

        per_version = {
            yuma_version: per_pair[(idx, yuma_version)]
            for yuma_version, _ in yuma_versions
        }

        case_start = row
        for chart_type in chart_types:
            for yuma_version, _ in yuma_versions:
                config, (dividends, bonds, incentives) = per_version[yuma_version]
                title = _decorated_case_name(case, yuma_version, config)
                if chart_type == "weights":
                    img = _plot_validator_server_weights(
                        validators=case.validators,
                        weights_epochs=case.weights_epochs,
                        servers=case.servers,
                        num_epochs=case.num_epochs,
                        case_name=title,
                        to_base64=True,
                    )
                elif chart_type == "dividends":
                    img = _plot_dividends(
                        num_epochs=case.num_epochs,
                        validators=case.validators,
                        dividends_per_validator=dividends,
                        case=title,
                        base_validator=case.base_validator,
                        to_base64=True,
                    )
                elif chart_type == "bonds":
                    img = _plot_bonds(
                        num_epochs=case.num_epochs,
                        validators=case.validators,
                        servers=case.servers,
                        bonds_per_epoch=bonds,
                        case_name=title,
                        to_base64=True,
                    )
                elif chart_type == "normalized_bonds":
                    img = _plot_bonds(
                        num_epochs=case.num_epochs,
                        validators=case.validators,
                        servers=case.servers,
                        bonds_per_epoch=bonds,
                        case_name=title,
                        to_base64=True,
                        normalize=True,
                    )
                elif chart_type == "incentives":
                    img = _plot_incentives(
                        servers=case.servers,
                        server_incentives_per_epoch=incentives,
                        num_epochs=case.num_epochs,
                        case_name=title,
                        to_base64=True,
                    )
                else:  # pragma: no cover
                    raise ValueError("Invalid chart type.")
                assert img is not None  # to_base64=True always returns html
                table_data[yuma_version].append(img)
            row += 1
        case_row_ranges.append((case_start, row - 1, idx))

    summary_table = pd.DataFrame(table_data)
    if draggable_table:
        full_html = _generate_draggable_html_table(
            table_data, summary_table, case_row_ranges
        )
    else:
        full_html = _generate_ipynb_table(table_data, summary_table, case_row_ranges)

    return HTML(full_html)
