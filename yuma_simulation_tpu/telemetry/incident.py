"""Cross-signal incident correlation: anomalies + SLO transitions +
typed fault ledger events joined into durable incident records.

The join rule (ISSUE 20): a typed FAULT ledger event (quarantine,
stall, restart, worker loss, drift — :data:`CAUSE_EVENTS`) opens an
incident; SYMPTOM records (``anomaly_detected`` from :mod:`.anomaly`,
``slo_alert`` burn transitions) attach to the best-matching open cause
within a causal window, preferring subject overlap, then trace-context
parentage (shared ``run_id`` and span adjacency), then time proximity.
Symptoms with no cause candidate stay unattributed — they NEVER open
incidents, which is what makes the clean-run zero-incident bound
provable: no typed fault, no incident.

Incident identity is ``<cause-class>:<subject>`` — deterministic
across processes, so a restarted controller re-deriving the same
stall folds into the SAME incident when ``incidents.jsonl`` appends
from both incarnations merge (readers keep the last record per id,
:func:`latest_incidents`).

Two consumption modes:

- offline — :func:`correlate` / :func:`correlate_bundle` are pure
  functions of ledger records; ``tools/incidentreport.py`` runs them
  on any bundle (drill bundles have no runtime engine);
- runtime — :class:`IncidentEngine` rides the replay controller's
  cycle: feeds the time-series store, ledgers detector anomalies,
  appends every incident state transition durably
  (:meth:`..flight.FlightRecorder.record_incident`, crash-safe via
  ``append_durable`` and segmented-rotation aware by construction —
  the sink lives at the bundle root), and keeps the
  ``incidents_open`` gauge / ``anomalies_total`` counter live for
  ``/healthz`` and ``/metrics``.
"""

from __future__ import annotations

import dataclasses
import logging
import pathlib
import time
from typing import Iterable, Optional

from yuma_simulation_tpu.telemetry.anomaly import (
    AnomalyEngine,
    default_replay_engine,
)
from yuma_simulation_tpu.telemetry.timeseries import TimeSeriesStore

logger = logging.getLogger(__name__)

#: Typed ledger events that OPEN incidents, mapped to their cause
#: class. Symptom streams (anomaly_detected, slo_alert) are
#: deliberately absent: a symptom without a typed cause is a question,
#: not an incident.
CAUSE_EVENTS = {
    "subnet_quarantined": "snapshot-corruption",
    "subnet_stalled": "subnet-stall",
    "controller_restarted": "process-loss",
    "worker_lost": "worker-loss",
    "unit_stalled": "engine-stall",
    "engine_drift": "canary-drift",
    "canary_failed": "canary-failure",
}

#: Symptom record types that attach to (never open) incidents.
SYMPTOM_EVENTS = ("anomaly_detected", "slo_alert")

#: cause class -> ledger events that RESOLVE it (subject-matched when
#: the resolver carries the subject field). Classes absent here stay
#: open until an operator closes them out-of-band:
#: snapshot-corruption resolves on its own quarantine (the blast is
#: contained the moment the blob is excluded), canary-drift never
#: auto-resolves (a drifting rung is not healed by time).
RESOLVE_EVENTS = {
    "subnet-stall": ("subnet_ingested", "watermark_advanced"),
    "process-loss": ("watermark_advanced", "window_swept"),
    "worker-loss": ("worker_spawned",),
    "engine-stall": ("unit_ok",),
}

#: Record fields that identify WHO an event is about, in match-priority
#: order; the first present one is the incident subject.
SUBJECT_KEYS = ("netuid", "unit", "worker", "host", "run", "bucket")

#: Fields unioned into the blast radius, per dimension.
BLAST_KEYS = {
    "netuids": "netuid",
    "units": "unit",
    "workers": "worker",
    "tenants": "tenant",
    "hosts": "host",
    "versions": "version",
}

#: Seconds around a cause inside which symptoms may attach.
DEFAULT_CAUSAL_WINDOW = 120.0

#: Most symptom-timeline entries one incident record retains.
MAX_SYMPTOMS = 32

#: Every ledger event type correlate() can act on — causes, symptoms,
#: resolvers. The runtime engine feeds ONLY these into its correlation
#: window; everything else in the ledger is noise to the join.
CORRELATION_EVENTS = (
    frozenset(CAUSE_EVENTS)
    | frozenset(SYMPTOM_EVENTS)
    | frozenset(ev for evs in RESOLVE_EVENTS.values() for ev in evs)
)

#: Most records the runtime engine keeps in its correlation window.
#: Bounds tick() cost on long soaks; cause records are never trimmed
#: (dropping one would flip its incident back to unseen).
MAX_CORRELATE_RECORDS = 4096


def _subject(record: dict) -> str:
    for key in SUBJECT_KEYS:
        if key in record and record[key] is not None:
            return f"{key}={record[key]}"
    return ""


def _timeline_entry(record: dict, kind: str) -> dict:
    entry = {"kind": kind, "event": record.get("event"),
             "t": record.get("t")}
    for key in ("series", "detail", "reason", "slo", "state", "netuid",
                "unit", "worker", "value"):
        if key in record:
            entry[key] = record[key]
    return entry


@dataclasses.dataclass
class Incident:
    """One correlated incident: cause, symptom timeline, blast radius,
    resolution state."""

    incident: str
    cause_class: str
    subject: str
    state: str  #: "open" | "resolved"
    opened_t: float
    cause: dict
    symptoms: list = dataclasses.field(default_factory=list)
    blast_radius: dict = dataclasses.field(default_factory=dict)
    resolved_t: Optional[float] = None
    resolution: str = ""
    run_id: str = ""
    span_id: str = ""

    def to_json(self) -> dict:
        rec = dataclasses.asdict(self)
        rec["symptoms"] = list(self.symptoms[:MAX_SYMPTOMS])
        return rec

    def _absorb(self, record: dict) -> None:
        for dim, key in BLAST_KEYS.items():
            if key in record and record[key] is not None:
                values = self.blast_radius.setdefault(dim, [])
                if record[key] not in values:
                    values.append(record[key])


def _relatedness(incident: Incident, symptom: dict) -> int:
    """Attachment score: 3 subject overlap, 2 span adjacency in the
    same run, 1 same run, 0 unrelated-but-in-window."""
    if _subject(symptom) and _subject(symptom) == incident.subject:
        return 3
    if symptom.get("run_id") and symptom.get("run_id") == incident.run_id:
        cause = incident.cause
        near = {cause.get("span_id"), cause.get("parent_id")} - {None, ""}
        if symptom.get("span_id") in near or symptom.get("parent_id") in near:
            return 2
        return 1
    return 0


def correlate(
    records: Iterable[dict],
    *,
    causal_window: float = DEFAULT_CAUSAL_WINDOW,
) -> list[Incident]:
    """Pure correlation over ledger-shaped records (any order):
    incidents keyed by ``(cause_class, subject)``, earliest matching
    cause wins, recurrences and symptoms fold into the timeline,
    resolution derived from matching recovery events."""
    ordered = sorted(
        (r for r in records if isinstance(r, dict)),
        key=lambda r: float(r.get("t") or 0.0),
    )
    incidents: dict[str, Incident] = {}
    for rec in ordered:
        cls = CAUSE_EVENTS.get(rec.get("event", ""))
        if cls is None:
            continue
        subject = _subject(rec)
        ident = f"{cls}:{subject}" if subject else cls
        inc = incidents.get(ident)
        if inc is None:
            inc = Incident(
                incident=ident,
                cause_class=cls,
                subject=subject,
                state="open",
                opened_t=float(rec.get("t") or 0.0),
                cause=dict(rec),
                run_id=str(rec.get("run_id") or ""),
                span_id=str(rec.get("span_id") or ""),
            )
            incidents[ident] = inc
        else:
            inc.symptoms.append(_timeline_entry(rec, "recurrence"))
        inc._absorb(rec)
    if not incidents:
        return []

    for rec in ordered:
        if rec.get("event") not in SYMPTOM_EVENTS:
            continue
        t = float(rec.get("t") or 0.0)
        best: Optional[tuple] = None
        for inc in incidents.values():
            if abs(t - inc.opened_t) > causal_window:
                continue
            score = _relatedness(inc, rec)
            if score < 1 and _subject(rec):
                continue  # a subject-bearing symptom must actually match
            key = (score, -abs(t - inc.opened_t))
            if best is None or key > best[0]:
                best = (key, inc)
        if best is not None:
            kind = "anomaly" if rec.get("event") == "anomaly_detected" \
                else "slo_transition"
            best[1].symptoms.append(_timeline_entry(rec, kind))
            best[1]._absorb(rec)

    for inc in incidents.values():
        if inc.cause_class == "snapshot-corruption":
            # The quarantine IS the mitigation: the corrupt blob is
            # durably excluded the instant the cause event exists.
            inc.state = "resolved"
            inc.resolved_t = inc.opened_t
            inc.resolution = "quarantined"
            continue
        resolvers = RESOLVE_EVENTS.get(inc.cause_class, ())
        if not resolvers:
            continue
        subject_key = inc.subject.split("=", 1)[0] if inc.subject else ""
        for rec in ordered:
            if rec.get("event") not in resolvers:
                continue
            t = float(rec.get("t") or 0.0)
            if t <= inc.opened_t:
                continue
            if subject_key and subject_key in rec and \
                    _subject(rec) != inc.subject:
                continue
            inc.state = "resolved"
            inc.resolved_t = t
            inc.resolution = str(rec.get("event"))
            break
    out = sorted(incidents.values(), key=lambda i: i.opened_t)
    for inc in out:
        inc.symptoms.sort(key=lambda e: float(e.get("t") or 0.0))
        del inc.symptoms[MAX_SYMPTOMS:]
    return out


def correlate_bundle(bundle, **kwargs) -> list[Incident]:
    """Offline correlation over a loaded :class:`..flight.Bundle`."""
    return correlate(bundle.ledger, **kwargs)


def unattributed_symptoms(
    records: Iterable[dict],
    incidents: Iterable[Incident],
) -> list[dict]:
    """Symptom records no incident's timeline absorbed — rendered (not
    failed) by incidentreport: a symptom without a cause is a question
    for the operator, not a correlation defect."""
    attached = set()
    for inc in incidents:
        for entry in inc.symptoms:
            attached.add((entry.get("event"), entry.get("t")))
    return [
        r
        for r in records
        if isinstance(r, dict)
        and r.get("event") in SYMPTOM_EVENTS
        and (r.get("event"), r.get("t")) not in attached
    ]


# ------------------------------------------------- durable record I/O


def latest_incidents(records: Iterable[dict]) -> list[dict]:
    """Fold raw ``incidents.jsonl`` append-order records to current
    state: last record per incident id wins (every transition
    re-appends the full state)."""
    latest: dict[str, dict] = {}
    for rec in records:
        if isinstance(rec, dict) and rec.get("incident"):
            latest[str(rec["incident"])] = rec
    return sorted(
        latest.values(), key=lambda r: float(r.get("opened_t") or 0.0)
    )


def load_incidents(directory) -> list[dict]:
    """Current incident states from a bundle directory's
    ``incidents.jsonl`` ([] when the sink does not exist — the
    unfaulted control arms never create it)."""
    from yuma_simulation_tpu.telemetry.flight import INCIDENTS_NAME
    from yuma_simulation_tpu.utils.checkpoint import read_jsonl_tolerant

    path = pathlib.Path(directory) / INCIDENTS_NAME
    if not path.exists():
        return []
    return latest_incidents(read_jsonl_tolerant(path))


def open_incident_count(directory) -> int:
    """How many incidents are currently open — the `/healthz` field."""
    return sum(
        1 for rec in load_incidents(directory) if rec.get("state") == "open"
    )


# ------------------------------------------------------ runtime engine


class IncidentEngine:
    """The controller-cycle runtime: time-series feed -> anomaly scan
    -> ledgered symptoms -> correlation -> durable incident records +
    live gauges. One instance per controller; everything host-side."""

    def __init__(
        self,
        ledger,
        recorder,
        *,
        registry=None,
        anomaly_engine: Optional[AnomalyEngine] = None,
        causal_window: float = DEFAULT_CAUSAL_WINDOW,
        source: str = "",
    ):
        from yuma_simulation_tpu.telemetry.metrics import get_registry

        self.ledger = ledger
        self.recorder = recorder
        self.registry = registry if registry is not None else get_registry()
        self.anomalies = (
            anomaly_engine if anomaly_engine is not None
            else default_replay_engine()
        )
        self.causal_window = float(causal_window)
        self.source = source
        self.store = TimeSeriesStore()
        self._known: dict[str, str] = {}  # incident id -> last state
        # Incremental correlation window: tick() consumes only the
        # ledger entries appended since the last tick (the in-memory
        # ledger is append-only, so an index cursor is exact) and keeps
        # the correlation-relevant ones, bounded — NOT the full ledger,
        # which would make every cycle O(ledger) and the run quadratic.
        self._ledger_cursor = 0
        self._window: list[dict] = []
        self._open_gauge = self.registry.gauge(
            "incidents_open",
            help="correlated incidents currently open in this bundle",
        )
        self._anomaly_counter = self.registry.counter(
            "anomalies_total",
            help="detector anomalies ledgered as anomaly_detected",
        )
        # Fold incidents a prior incarnation already recorded so a
        # restarted controller re-deriving the same incident appends a
        # transition only when the state actually moved.
        try:
            for rec in load_incidents(self.recorder.directory):
                self._known[str(rec["incident"])] = str(
                    rec.get("state") or "open"
                )
        except Exception:
            logger.warning("prior incident reload failed", exc_info=True)

    def feed_snapshot(self, now: Optional[float] = None) -> int:
        """Fold one live registry snapshot (+ dispatch sketches) into
        the time-series store; returns how many anomalies fired and
        were ledgered."""
        from yuma_simulation_tpu.telemetry.metrics import _next_seq
        from yuma_simulation_tpu.telemetry.slo import dispatch_snapshot
        from yuma_simulation_tpu.utils.logging import log_event

        # Same seq counter as the persisted snapshot paths (metrics.py),
        # so live and bundle records share one dedupe identity — without
        # it the store falls back to (source, rounded t) and two
        # snapshots on a coarse/stepped clock silently collapse.
        record = {
            "t": round(now if now is not None else time.time(), 6),
            "seq": _next_seq(),
            **self.registry.snapshot(),
        }
        sketches = dispatch_snapshot()
        if sketches:
            record["dispatch_sketches"] = sketches
        self.store.ingest_snapshot(record, source=self.source or "live")
        fired = self.anomalies.scan(self.store)
        for a in fired:
            self.ledger.append(
                "anomaly_detected",
                kind=a.kind,
                series=a.series,
                value=a.value,
                baseline=a.baseline,
                threshold=a.threshold,
                window=a.window,
                detail=a.detail,
            )
            log_event(
                logger,
                "anomaly_detected",
                kind=a.kind,
                series=a.series,
                detail=a.detail,
            )
            self._anomaly_counter.inc()
        return len(fired)

    def _advance_window(self) -> list[dict]:
        """Fold ledger entries appended since the last tick into the
        bounded correlation window and return it."""
        entries = self.ledger.entries()
        for rec in entries[self._ledger_cursor:]:
            if isinstance(rec, dict) and \
                    rec.get("event") in CORRELATION_EVENTS:
                self._window.append(rec)
        self._ledger_cursor = len(entries)
        if len(self._window) > MAX_CORRELATE_RECORDS:
            causes = [
                r for r in self._window if r.get("event") in CAUSE_EVENTS
            ]
            rest = [
                r for r in self._window
                if r.get("event") not in CAUSE_EVENTS
            ]
            keep = max(MAX_CORRELATE_RECORDS - len(causes), 0)
            self._window = causes + rest[len(rest) - keep:]
        return self._window

    def tick(self, now: Optional[float] = None) -> list[Incident]:
        """One correlation pass: feed the snapshot, re-derive incidents
        from the correlation window (pure + idempotent; fed
        incrementally and bounded, so a cycle costs O(window), not
        O(ledger lifetime)), durably append every state transition,
        ledger the typed open/resolve events, refresh the gauge.
        Returns the current incident set."""
        from yuma_simulation_tpu.utils.logging import log_event

        self.feed_snapshot(now)
        incidents = correlate(
            self._advance_window(), causal_window=self.causal_window
        )
        for inc in incidents:
            prior = self._known.get(inc.incident)
            if prior == inc.state:
                continue
            self._known[inc.incident] = inc.state
            self.recorder.record_incident(inc.to_json())
            if prior is None:
                self.ledger.append(
                    "incident_opened",
                    incident=inc.incident,
                    cause_class=inc.cause_class,
                    cause_event=str(inc.cause.get("event")),
                    subject=inc.subject,
                    state=inc.state,
                )
                log_event(
                    logger,
                    "incident_opened",
                    incident=inc.incident,
                    cause_class=inc.cause_class,
                )
            if inc.state == "resolved":
                self.ledger.append(
                    "incident_resolved",
                    incident=inc.incident,
                    cause_class=inc.cause_class,
                    resolution=inc.resolution,
                )
                log_event(
                    logger,
                    "incident_resolved",
                    incident=inc.incident,
                    resolution=inc.resolution,
                )
        self._open_gauge.set(
            sum(1 for i in incidents if i.state == "open")
        )
        return incidents
