"""Robust anomaly detectors over :mod:`.timeseries` series.

Design rule: every detector declares its window and a minimum sample
count and stays SILENT until both are met, so a clean run is provably
quiet (the clean-soak bound in tests/unit/test_incidents.py pins zero
firings on unfaulted traffic). Detectors are stateful scanners — each
remembers how far into a series it has read and whether it is latched
inside an excursion, so a sustained level shift fires ONCE and the
baseline reseeds after recovery instead of alarming every sample.

The four families (ISSUE 20):

- :class:`MadDetector` — rolling median/MAD deviation: a sample firing
  means ``|v - median| > threshold * max(MAD, mad_floor)`` against the
  trailing window. Median/MAD (not mean/stddev) so a single prior
  outlier cannot inflate the baseline and mask the next one.
- :class:`RateOfChangeDetector` — per-second slope between adjacent
  samples beyond a declared ceiling.
- :class:`CounterStallDetector` — a cumulative counter frozen for a
  declared wall-clock horizon while a companion activity counter keeps
  advancing (progress stopped, process alive).
- :class:`SaturationDetector` — a gauge pinned at/above a fraction of
  its declared capacity for ``min_samples`` consecutive samples.

Detectors return typed :class:`Anomaly` values; the runtime engine
(:mod:`.incident`) ledgers them as ``anomaly_detected`` records. All
host-side, zero compiles.
"""

from __future__ import annotations

import dataclasses
import statistics
from typing import Optional

from yuma_simulation_tpu.telemetry.timeseries import TimeSeriesStore


def _fresh_samples(
    store: TimeSeriesStore, key: str, last: Optional[tuple]
) -> tuple:
    """``(samples, cursor)``: the samples of `key` strictly after the
    ``(t, order)`` identity `last`, and the advanced cursor. Cursoring
    is by sample IDENTITY, never by index into the series — the store's
    rings evict once full, so an index cursor pins at ``len(series)``
    forever and the detector goes silently blind in exactly the
    long-running regime it exists for."""
    samples = store.samples(key)
    if last is not None:
        samples = tuple(s for s in samples if (s[0], s[1]) > last)
    if not samples:
        return (), last
    return samples, (samples[-1][0], samples[-1][1])


@dataclasses.dataclass(frozen=True)
class Anomaly:
    """One detector firing on one series sample."""

    kind: str  #: detector family: mad / rate_of_change / counter_stall / saturation
    series: str  #: the time-series key scanned
    t: float  #: wall clock of the offending sample
    value: float  #: the offending sample's value
    baseline: float  #: what the detector expected (median, prior, cap)
    threshold: float  #: the declared bound the sample exceeded
    window: int  #: declared window (samples or seconds, per kind)
    detail: str = ""  #: one human line of context

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class MadDetector:
    """Rolling median/MAD excursion detector with a one-shot latch.

    ``mad_floor`` is the robustness escape hatch for near-constant
    series: a series that sat at exactly 0.0 for the whole window has
    MAD 0, and without a floor ANY change would fire — the floor is the
    smallest deviation worth calling anomalous at all."""

    kind = "mad"

    def __init__(
        self,
        series: str,
        *,
        window: int = 32,
        min_samples: int = 12,
        threshold: float = 8.0,
        mad_floor: float = 1.0,
    ):
        if min_samples < 4 or window < min_samples:
            raise ValueError(
                f"need window >= min_samples >= 4, got "
                f"window={window} min_samples={min_samples}"
            )
        self.series = series
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.threshold = float(threshold)
        self.mad_floor = float(mad_floor)
        self._baseline: list[float] = []
        self._last: Optional[tuple] = None
        self._latched = False

    def observe(self, t: float, value: float) -> Optional[Anomaly]:
        """Feed one sample (in series order); an :class:`Anomaly` back
        iff this sample opens a NEW excursion."""
        if len(self._baseline) < self.min_samples:
            self._baseline.append(value)
            return None
        med = statistics.median(self._baseline)
        mad = statistics.median(abs(v - med) for v in self._baseline)
        bound = self.threshold * max(mad, self.mad_floor)
        excursion = abs(value - med) > bound
        if not excursion:
            # Recovered (or never deviated): the sample joins the
            # baseline and any latch releases — the NEXT excursion is a
            # new incident, judged against a reseeded window.
            self._baseline.append(value)
            if len(self._baseline) > self.window:
                del self._baseline[: len(self._baseline) - self.window]
            self._latched = False
            return None
        # Excursion sample: deliberately NOT folded into the baseline —
        # a sustained shift must not normalize itself into silence
        # before a recovery was ever seen.
        if self._latched:
            return None
        self._latched = True
        return Anomaly(
            kind=self.kind,
            series=self.series,
            t=t,
            value=value,
            baseline=med,
            threshold=bound,
            window=self.window,
            detail=f"|{value:.6g} - median {med:.6g}| > {bound:.6g} "
            f"({self.threshold:g} x MAD)",
        )

    def scan(self, store: TimeSeriesStore) -> list[Anomaly]:
        out = []
        fresh, self._last = _fresh_samples(store, self.series, self._last)
        for t, _order, v in fresh:
            a = self.observe(t, v)
            if a is not None:
                out.append(a)
        return out


class RateOfChangeDetector:
    """Adjacent-sample slope beyond ``max_per_second``, latched per
    excursion like :class:`MadDetector`."""

    kind = "rate_of_change"

    def __init__(
        self,
        series: str,
        *,
        max_per_second: float,
        min_samples: int = 4,
    ):
        if max_per_second <= 0:
            raise ValueError("max_per_second must be positive")
        self.series = series
        self.max_per_second = float(max_per_second)
        self.min_samples = int(min_samples)
        self._last: Optional[tuple] = None
        self._prev: Optional[tuple] = None
        self._seen = 0
        self._latched = False

    def observe(self, t: float, value: float) -> Optional[Anomaly]:
        prev, self._prev = self._prev, (t, value)
        self._seen += 1
        if prev is None or self._seen <= self.min_samples:
            return None
        dt = t - prev[0]
        if dt <= 0:
            return None
        rate = abs(value - prev[1]) / dt
        if rate <= self.max_per_second:
            self._latched = False
            return None
        if self._latched:
            return None
        self._latched = True
        return Anomaly(
            kind=self.kind,
            series=self.series,
            t=t,
            value=value,
            baseline=prev[1],
            threshold=self.max_per_second,
            window=self.min_samples,
            detail=f"rate {rate:.6g}/s > {self.max_per_second:g}/s",
        )

    def scan(self, store: TimeSeriesStore) -> list[Anomaly]:
        out = []
        fresh, self._last = _fresh_samples(store, self.series, self._last)
        for t, _order, v in fresh:
            a = self.observe(t, v)
            if a is not None:
                out.append(a)
        return out


class CounterStallDetector:
    """A cumulative counter frozen for ``horizon_seconds`` of samples
    while the activity counter advanced by at least ``min_activity`` —
    distinguishes "progress stopped" from "nothing was asked". Fires
    once per freeze; reseeds when the target advances again.

    NOT wired by default anywhere: a stall pair is an explicit claim
    about two specific counters, so callers opt series pairs in."""

    kind = "counter_stall"

    def __init__(
        self,
        series: str,
        activity_series: str,
        *,
        horizon_seconds: float = 30.0,
        min_activity: float = 1.0,
    ):
        self.series = series
        self.activity_series = activity_series
        self.horizon_seconds = float(horizon_seconds)
        self.min_activity = float(min_activity)
        self._latched = False

    def scan(self, store: TimeSeriesStore) -> list[Anomaly]:
        target = store.series(self.series)
        activity = store.series(self.activity_series)
        if not target or not activity:
            return []
        t_now, v_now = target[-1]
        frozen_since = t_now
        for t, v in reversed(target):
            if v != v_now:
                break
            frozen_since = t
        frozen_for = t_now - frozen_since
        moved = self._activity_delta(activity, frozen_since)
        stalled = (
            frozen_for >= self.horizon_seconds
            and moved >= self.min_activity
        )
        if not stalled:
            self._latched = False
            return []
        if self._latched:
            return []
        self._latched = True
        return [
            Anomaly(
                kind=self.kind,
                series=self.series,
                t=t_now,
                value=v_now,
                baseline=v_now,
                threshold=self.horizon_seconds,
                window=int(self.horizon_seconds),
                detail=f"frozen {frozen_for:.1f}s at {v_now:.6g} while "
                f"{self.activity_series} advanced {moved:.6g}",
            )
        ]

    def _activity_delta(self, activity, since_t: float) -> float:
        baseline = None
        for t, v in activity:
            if t <= since_t:
                baseline = v
        if baseline is None:
            baseline = activity[0][1]
        return activity[-1][1] - baseline


class SaturationDetector:
    """Gauge pinned at/above ``high_fraction * capacity`` for
    ``min_samples`` consecutive samples; fires once per saturation."""

    kind = "saturation"

    def __init__(
        self,
        series: str,
        *,
        capacity: float,
        high_fraction: float = 0.95,
        min_samples: int = 3,
    ):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.series = series
        self.capacity = float(capacity)
        self.high_fraction = float(high_fraction)
        self.min_samples = int(min_samples)
        self._last: Optional[tuple] = None
        self._run = 0
        self._latched = False

    def scan(self, store: TimeSeriesStore) -> list[Anomaly]:
        out = []
        bound = self.high_fraction * self.capacity
        fresh, self._last = _fresh_samples(store, self.series, self._last)
        for t, _order, v in fresh:
            if v >= bound:
                self._run += 1
                if self._run >= self.min_samples and not self._latched:
                    self._latched = True
                    out.append(
                        Anomaly(
                            kind=self.kind,
                            series=self.series,
                            t=t,
                            value=v,
                            baseline=self.capacity,
                            threshold=bound,
                            window=self.min_samples,
                            detail=f"{v:.6g} >= {bound:.6g} "
                            f"({self.high_fraction:.0%} of capacity "
                            f"{self.capacity:g}) for {self._run} samples",
                        )
                    )
            else:
                self._run = 0
                self._latched = False
        return out


class AnomalyEngine:
    """A set of detectors scanned together against one store. Purely a
    container — the incident engine (:mod:`.incident`) owns ledgering
    what this returns."""

    def __init__(self, detectors=()):
        self.detectors = list(detectors)

    def add(self, detector) -> "AnomalyEngine":
        self.detectors.append(detector)
        return self

    def scan(self, store: TimeSeriesStore) -> list[Anomaly]:
        out: list[Anomaly] = []
        for d in self.detectors:
            out.extend(d.scan(store))
        out.sort(key=lambda a: a.t)
        return out


def default_replay_engine() -> AnomalyEngine:
    """The controller's default wiring: deliberately conservative — one
    MAD detector on the freshness gauge (the SIGKILL/stall symptom
    surface). Everything else is opt-in per deployment; a default that
    fires on healthy soak traffic would poison the clean-run bound."""
    return AnomalyEngine(
        [
            MadDetector(
                "gauge:replay_staleness_seconds",
                window=64,
                min_samples=12,
                threshold=8.0,
                mad_floor=2.0,
            ),
        ]
    )
