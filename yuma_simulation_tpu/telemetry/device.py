"""Device/compile telemetry: HBM, live buffers, jit-cache deltas.

A sweep's memory and compile story is invisible in the log stream: HBM
peaks live in ``device.memory_stats()`` (TPU/GPU only — CPU returns
None), buffer leaks in ``jax.live_arrays()``, and silent re-traces in
the jit caches :class:`..utils.profiling.RecompilationSentinel` watches.
This module samples all three AT SPAN BOUNDARIES — host-level, between
dispatches, never inside traced code — so the numbers land in the
metrics registry and the flight-recorder bundle without perturbing the
zero-warm-repeat compile budgets.

Everything degrades gracefully off-TPU: absent/None ``memory_stats``
yields ``device_peak_bytes=None`` in the sample (and leaves the gauge
untouched), a single-device CPU mesh is just `num_devices=1`.
"""

from __future__ import annotations

from typing import Optional

from yuma_simulation_tpu.telemetry.metrics import (
    MetricsRegistry,
    get_registry,
)


def sample_device_telemetry() -> dict:
    """One host-side snapshot of the backend's memory/buffer state.

    Returns a flat dict: ``backend``, ``num_devices``,
    ``device_peak_bytes`` (max over devices, None when no device
    exposes memory stats — every CPU build), ``device_bytes_in_use``
    (sum, same None contract) and ``live_buffers`` (live `jax.Array`
    count, None when introspection is unavailable). Never raises: a
    backend probe failure degrades to the all-None sample.
    """
    sample: dict = {
        "backend": None,
        "num_devices": 0,
        "device_peak_bytes": None,
        "device_bytes_in_use": None,
        "live_buffers": None,
    }
    try:
        import jax

        devices = jax.devices()
        sample["backend"] = jax.default_backend()
        sample["num_devices"] = len(devices)
    except Exception:
        return sample
    peaks: list[int] = []
    in_use: list[int] = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue  # CPU devices report None — the graceful path
        peaks.append(int(stats.get("peak_bytes_in_use", 0)))
        in_use.append(int(stats.get("bytes_in_use", 0)))
    if peaks:
        sample["device_peak_bytes"] = max(peaks)
        sample["device_bytes_in_use"] = sum(in_use)
    try:
        sample["live_buffers"] = len(jax.live_arrays())
    except Exception:
        pass
    return sample


def record_device_telemetry(
    registry: Optional[MetricsRegistry] = None,
) -> dict:
    """Sample and fold into the registry: ``device_peak_bytes`` /
    ``device_bytes_in_use`` / ``live_buffers`` gauges (None samples
    leave the gauges untouched rather than zeroing a real prior
    reading). Returns the raw sample."""
    reg = registry if registry is not None else get_registry()
    sample = sample_device_telemetry()
    if sample["device_peak_bytes"] is not None:
        reg.gauge(
            "device_peak_bytes", help="max per-device peak_bytes_in_use"
        ).set(sample["device_peak_bytes"])
    if sample["device_bytes_in_use"] is not None:
        reg.gauge(
            "device_bytes_in_use", help="sum of per-device bytes_in_use"
        ).set(sample["device_bytes_in_use"])
    if sample["live_buffers"] is not None:
        reg.gauge(
            "live_buffers", help="live jax.Array count at last sample"
        ).set(sample["live_buffers"])
    return sample


class CompileTracker:
    """Incremental jit-cache growth observer — the observability sibling
    of :class:`..utils.profiling.RecompilationSentinel` (which ENFORCES
    a budget; this only counts). Track the jitted entry points of a hot
    path, call :meth:`record` at span boundaries, and every new cache
    entry since the previous call lands on the ``recompiles`` counter.

    Per-function positive deltas only (an eviction elsewhere must not
    hide a genuine re-trace), same as the sentinel.
    """

    def __init__(self, *functions, registry: Optional[MetricsRegistry] = None):
        if not functions:
            raise ValueError("CompileTracker needs at least one jitted fn")
        for fn in functions:
            if not hasattr(fn, "_cache_size"):
                raise TypeError(
                    f"{getattr(fn, '__name__', fn)!r} exposes no "
                    "_cache_size(); pass the jax.jit-wrapped callable"
                )
        self._functions = functions
        self._registry = registry
        self._baseline = [fn._cache_size() for fn in functions]

    def record(self) -> int:
        """New cache entries since the last call (or construction);
        increments the ``recompiles`` counter by that delta."""
        current = [fn._cache_size() for fn in self._functions]
        new = sum(
            max(0, a - b) for a, b in zip(current, self._baseline)
        )
        self._baseline = current
        if new:
            reg = (
                self._registry
                if self._registry is not None
                else get_registry()
            )
            reg.counter(
                "recompiles", help="new jit-cache entries observed"
            ).inc(new)
        return new
