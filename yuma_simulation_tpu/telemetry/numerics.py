"""Numerics flight recorder: per-epoch tensor-stat telemetry.

The paper's core contract — every engine rung produces bitwise-identical
consensus weights, incentives and dividends — was enforced only in
tests: no production run recorded what the tensors looked like, which
rung produced them, or whether a re-execution reproduced the primary's
bits. This module is the always-on capture half of that observability
(the canary scheduler in :mod:`..resilience.supervisor` and the
``tools/driftreport.py`` gate are the comparison half):

- :func:`sketch_over_epochs` / :func:`epoch_sketch` compute a
  :class:`..simulation.carry.NumericsSketch` per epoch per lane —
  finite fraction, min/max/absmax, and the bit-cast-u32 reduction
  fingerprint (:mod:`...ops.fingerprint`) — **inside the existing
  jitted scan bodies**: a handful of scalar reductions per epoch, no
  host syncs, no extra dispatches, zero warm-repeat compiles (the
  capture is part of the one traced program).
- Every reduction is exact and order-independent (integer counts,
  wrapping-u32 bit sums, min/max), so sketches are bitwise invariant
  across monolithic, chunk-streamed and miner-sharded execution of the
  same case — merging chunked captures is concatenation along the
  epoch axis (:func:`concat_sketches`), and a sharded psum of the
  fingerprint equals the unsharded reduce by construction.
- :func:`sketch_records` serializes host-fetched sketches into the
  ``numerics.jsonl`` records the flight bundle carries
  (:meth:`..flight.FlightRecorder.record_numerics`), and
  :func:`first_divergence` / :func:`diff_records` localize the first
  divergent epoch and per-lane ulp distance between two captures —
  what the cross-engine canary and ``driftreport --check`` act on.

One switch disables the whole stream: ``YUMA_NUMERICS=0`` (env). The
engines take the resolved flag as a static jit argument, so flipping it
selects a different (cached) program rather than retracing warm paths.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

#: Stream names captured per engine dispatch, in capture order. The
#: fused kernel emits per-epoch consensus only when asked to save it,
#: so records compare on the intersection of streams present.
NUMERICS_STREAMS = ("dividends", "consensus")


def numerics_enabled() -> bool:
    """The one config/env switch: ``YUMA_NUMERICS=0`` (or ``false``/
    ``off``) disables per-epoch numerics capture everywhere. Default
    on — the capture is a handful of exact scalar reductions per epoch,
    and a production system that can silently flip a dividend cell
    without telemetry has no numerics observability at all."""
    return os.environ.get("YUMA_NUMERICS", "1").strip().lower() not in (
        "0",
        "false",
        "off",
    )


# ------------------------------------------------------------------ capture
# jit-safe: called inside the engines' traced bodies only.


def epoch_sketch(x):
    """The per-epoch sketch of one tensor (all axes reduced) — the
    spelling the XLA scan step uses. Exact/order-independent reductions
    only (see the module docstring), shared with
    :func:`sketch_over_epochs` so stacked and in-scan captures of the
    same bits are bitwise identical."""
    return sketch_over_epochs(x[None], epoch_axis=0, _squeeze=True)


def sketch_over_epochs(x, epoch_axis: int, _squeeze: bool = False):
    """Per-epoch :class:`..simulation.carry.NumericsSketch` of a
    stacked stream: every axis AFTER `epoch_axis` is reduced per epoch,
    leading axes (batch lanes) are kept. `[E, V] -> [E]` sketches,
    `[B, E, V] -> [B, E]` sketches."""
    import jax.numpy as jnp

    from yuma_simulation_tpu.ops.fingerprint import fingerprint_u32
    from yuma_simulation_tpu.simulation.carry import NumericsSketch

    x = jnp.asarray(x)
    axes = tuple(range(epoch_axis + 1, x.ndim))
    size = 1
    for d in x.shape[epoch_axis + 1 :]:
        size *= int(d)
    size = max(1, size)
    finite = jnp.sum(
        jnp.isfinite(x).astype(jnp.int32), axis=axes, dtype=jnp.int32
    )
    # min/max over a stream with NaNs would poison the stats exactly
    # where they matter; the masked forms keep them informative while
    # finite_frac carries the failure signal. absmax of an all-NaN
    # epoch reads 0 by the same masking.
    zero = jnp.zeros((), x.dtype)
    # Dtype-pinned infinities (jaxlint JX005): a weak Python-float inf
    # must not promote the stats under the x64 parity harness.
    inf = jnp.asarray(float("inf"), dtype=x.dtype)
    ok = jnp.isfinite(x)
    sketch = NumericsSketch(
        finite_frac=(finite.astype(x.dtype) / size),
        lo=jnp.min(jnp.where(ok, x, inf), axis=axes),
        hi=jnp.max(jnp.where(ok, x, -inf), axis=axes),
        absmax=jnp.max(jnp.where(ok, jnp.abs(x), zero), axis=axes),
        fingerprint=fingerprint_u32(x, axes=axes),
    )
    if _squeeze:
        import jax

        sketch = jax.tree.map(lambda leaf: leaf[0], sketch)
    return sketch


def capture_streams(
    streams: dict, epoch_axis: Optional[int] = None
) -> dict:
    """Sketch every non-None stream. `epoch_axis=None` means the inputs
    are single-epoch tensors (the in-scan spelling); an int means
    stacked streams (`[.., E, ..]`, the fused-wrapper spelling)."""
    out = {}
    for name, x in streams.items():
        if x is None:
            continue
        out[name] = (
            epoch_sketch(x)
            if epoch_axis is None
            else sketch_over_epochs(x, epoch_axis)
        )
    return out


# --------------------------------------------------------------- host side


def to_host(sketches: dict) -> dict:
    """Fetch a captured sketch pytree to numpy (leaf-wise)."""
    import jax

    return jax.tree.map(np.asarray, sketches)


def concat_sketches(chunks: list) -> dict:
    """Merge per-chunk sketch captures of one stream set along the
    epoch axis (the LAST axis of every leaf) — the chunk-invariant
    merge: per-epoch values concatenate, nothing is re-reduced."""
    import jax

    if not chunks:
        return {}
    return jax.tree.map(
        lambda *leaves: np.concatenate(
            [np.atleast_1d(np.asarray(leaf)) for leaf in leaves], axis=-1
        ),
        *chunks,
    )


def _lane_lists(arr: np.ndarray) -> list:
    """`[E]` or `[L, E]` -> per-lane python lists (always 2-D)."""
    a = np.atleast_2d(np.asarray(arr))
    return [lane.tolist() for lane in a]


def sketch_records(
    sketches: dict,
    *,
    unit: int,
    lanes,
    engine: str,
    role: str = "primary",
    label: str = "",
) -> list:
    """Serialize one dispatch's host-fetched sketches into
    ``numerics.jsonl`` records: one record per stream, per-lane arrays
    nested (`fingerprint[lane][epoch]`, uint32 as ints). `role` is
    "primary" or "canary"; `lanes` the `[lo, hi)` global-lane window."""
    records = []
    for stream, sk in sorted(sketches.items()):
        fp = np.atleast_2d(np.asarray(sk.fingerprint)).astype(np.uint32)
        records.append(
            {
                "unit": int(unit),
                "lanes": [int(lanes[0]), int(lanes[1])],
                "stream": stream,
                "engine": engine,
                "role": role,
                "label": label,
                "epochs": int(fp.shape[-1]),
                "fingerprint": [lane.tolist() for lane in fp],
                "finite_frac": _lane_lists(sk.finite_frac),
                "min": _lane_lists(sk.lo),
                "max": _lane_lists(sk.hi),
                "absmax": _lane_lists(sk.absmax),
            }
        )
    return records


def first_divergence(fp_a, fp_b) -> Optional[tuple]:
    """First epoch where two per-epoch fingerprint sequences differ,
    with the ulp distance there — `(epoch, ulp)` or None when bitwise
    identical. Length mismatches diverge at the shorter length."""
    from yuma_simulation_tpu.ops.fingerprint import ulp_delta

    a = np.asarray(fp_a, np.uint32).ravel()
    b = np.asarray(fp_b, np.uint32).ravel()
    n = min(a.size, b.size)
    neq = np.nonzero(a[:n] != b[:n])[0]
    if neq.size:
        e = int(neq[0])
        return e, ulp_delta(int(a[e]), int(b[e]))
    if a.size != b.size:
        return n, 0
    return None


def compare_sketches(primary: dict, canary: dict) -> dict:
    """Per-stream divergences between two host-fetched sketch sets of
    the SAME workload (a primary dispatch and its cross-engine canary):
    ``{stream: [{"lane", "first_divergent_epoch", "ulp_distance"}, ...]}``
    over the INTERSECTION of captured streams (the fused kernel emits a
    per-epoch consensus stream only when asked to save it). Empty dict =
    bitwise identical everywhere the two captures overlap."""
    out: dict = {}
    for stream in sorted(set(primary) & set(canary)):
        fa = np.atleast_2d(np.asarray(primary[stream].fingerprint))
        fb = np.atleast_2d(np.asarray(canary[stream].fingerprint))
        divergences = []
        for lane in range(max(fa.shape[0], fb.shape[0])):
            a = fa[lane] if lane < fa.shape[0] else np.empty(0, np.uint32)
            b = fb[lane] if lane < fb.shape[0] else np.empty(0, np.uint32)
            div = first_divergence(a, b)
            if div is not None:
                divergences.append(
                    {
                        "lane": lane,
                        "first_divergent_epoch": div[0],
                        "ulp_distance": div[1],
                    }
                )
        if divergences:
            out[stream] = divergences
    return out


def numerics_identity(rec: dict) -> tuple:
    """The ONE record-identity spelling for the ``numerics.jsonl``
    stream: ``(unit, lanes, stream, role, label)`` — deliberately
    engine-FREE, so a unit re-executed on a demoted rung REPLACES its
    prior capture (newest wins) instead of leaving a stale
    other-engine primary behind for a later canary to mispair
    against. Used by both the flight-recorder merge and the
    driftreport comparison (two spellings would fork the dedupe from
    the gate)."""
    return (
        rec.get("unit"),
        tuple(rec.get("lanes") or ()),
        rec.get("stream"),
        rec.get("role"),
        rec.get("label", ""),
    )


def check_numerics_records(records) -> list[str]:
    """Structural rot in serialized ``numerics.jsonl`` records — the
    ONE shared validator behind both :func:`..flight.check_bundle`'s
    numerics block and ``tools/driftreport.py --check``'s exit-2
    class (two spellings of the comparison basis would fork the gate
    from the cross-check exactly the way forked reductions fork the
    consensus). A record that cannot be compared is not a pass."""
    problems: list[str] = []
    for i, rec in enumerate(records):
        for field in ("stream", "engine", "role"):
            if not rec.get(field):
                problems.append(f"numerics[{i}] names no {field}")
        if rec.get("role") not in ("primary", "canary", None):
            problems.append(
                f"numerics[{i}] has unknown role {rec.get('role')!r}"
            )
        fp = rec.get("fingerprint")
        if not isinstance(fp, list) or not fp:
            problems.append(f"numerics[{i}] carries no fingerprint lanes")
            continue
        epochs = rec.get("epochs")
        for lane in fp:
            if not isinstance(lane, list) or (
                isinstance(epochs, int) and len(lane) != epochs
            ):
                problems.append(
                    f"numerics[{i}] fingerprint lane length mismatches "
                    f"declared epochs={epochs!r}"
                )
                break
    return problems


def diff_records(primary: dict, canary: dict) -> list:
    """Per-lane divergences between two ``numerics.jsonl`` records of
    the same (unit, stream): a list of
    ``{"lane", "first_divergent_epoch", "ulp_distance"}`` dicts (empty
    = bitwise identical). Lanes index within the record's window; add
    ``lanes[0]`` for the sweep-global lane."""
    out = []
    fa, fb = primary.get("fingerprint", []), canary.get("fingerprint", [])
    for lane in range(max(len(fa), len(fb))):
        a = fa[lane] if lane < len(fa) else []
        b = fb[lane] if lane < len(fb) else []
        div = first_divergence(a, b)
        if div is not None:
            out.append(
                {
                    "lane": lane,
                    "first_divergent_epoch": div[0],
                    "ulp_distance": div[1],
                }
            )
    return out
