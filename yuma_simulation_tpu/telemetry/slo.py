"""SLOs over mergeable sketches: objectives, burn rates, degradation.

The metrics registry (:mod:`.metrics`) says what the process DID —
counts, rates, fixed-bucket histograms for Prometheus. Nothing says
whether any of it is ACCEPTABLE. This module is the judgment layer:

- :class:`LatencySketch` — a log-bucketed quantile sketch (DDSketch
  family): geometric buckets sized for a declared relative-error bound,
  so ``merge`` is exact count addition — associative and commutative
  across threads, processes, and fleet hosts — and any quantile of the
  merged population is within the bound of the true empirical quantile.
  The fixed-bucket :class:`.metrics.Histogram` stays for Prometheus
  exposition; sketches feed SLOs (and serialize losslessly, so host
  bundles can be re-aggregated after the fact);
- :class:`SLOSpec` — one declarative objective: the fraction of events
  that must be *good* (a duration under its threshold, or an explicit
  good/bad event), with fast/slow burn-rate windows and thresholds (the
  standard SRE multi-window burn-rate alert);
- :class:`SLOEngine` — the evaluator: ingests observations on an
  injectable clock, maintains per-second windowed good/bad counts,
  computes ``burn = bad_fraction / error_budget`` per window, and walks
  each SLO through ``ok -> slow_burn -> fast_burn`` and back. Every
  transition is a typed ``event=slo_alert``/``slo_recovered`` record
  plus metrics (``slo_alerts_total``, ``slo_fast_burn_active``), and
  specs marked ``degrade=True`` drive the serving tier's admission
  shedding while fast-burning — observability driving degradation, not
  just describing it.

The process engine (:func:`get_slo_engine`) is fed by the supervisor
(``unit_seconds`` per accepted unit), the recompilation sentinel
(``compile_seconds`` — the cold-start SLO, prefiguring ROADMAP item 2),
and the serving tier (request latency / error / shed streams); its
state publishes as ``slo.json`` in every flight bundle and is gated by
``python -m tools.sloreport BUNDLE --check``.

Host-side only, zero new dependencies, all state under locks.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time
from typing import Callable, Optional, Sequence

from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)

#: Default quantile relative-error bound: 1% — tight enough that a p99
#: read off a sketch is the p99, loose enough that a sweep's worth of
#: durations fits in tens of buckets.
DEFAULT_RELATIVE_ACCURACY = 0.01


class LatencySketch:
    """Log-bucketed quantile sketch with a declared relative-error
    bound (see the module docstring). Values are wall-clock seconds
    (any positive magnitude works); non-positive values land in a
    dedicated zero bucket so a clock hiccup cannot crash the math."""

    def __init__(
        self, relative_accuracy: float = DEFAULT_RELATIVE_ACCURACY
    ):
        if not (0.0 < relative_accuracy < 1.0):
            raise ValueError(
                "relative_accuracy must be in (0, 1), got "
                f"{relative_accuracy}"
            )
        self.relative_accuracy = float(relative_accuracy)
        self._gamma = (1.0 + self.relative_accuracy) / (
            1.0 - self.relative_accuracy
        )
        self._log_gamma = math.log(self._gamma)
        self._lock = threading.Lock()
        self._counts: dict[int, int] = {}
        self._zero = 0
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    # -- ingest ---------------------------------------------------------

    def _index(self, value: float) -> int:
        return int(math.ceil(math.log(value) / self._log_gamma))

    def _representative(self, index: int) -> float:
        # Midpoint of (gamma^(i-1), gamma^i] in the relative metric:
        # |rep - v| / v <= relative_accuracy for every v in the bucket.
        return 2.0 * self._gamma**index / (self._gamma + 1.0)

    def observe(self, value) -> None:
        v = float(value)
        with self._lock:
            self._count += 1
            self._sum += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            if v <= 0.0:
                self._zero += 1
                return
            idx = self._index(v)
            self._counts[idx] = self._counts.get(idx, 0) + 1

    # -- algebra --------------------------------------------------------

    def merge(self, other: "LatencySketch") -> "LatencySketch":
        """Fold `other` into this sketch (count addition — exact,
        associative, commutative). The accuracy parameters must match:
        merging mismatched bucket bases would silently void the error
        bound."""
        if not isinstance(other, LatencySketch):
            raise TypeError(f"cannot merge {type(other).__name__}")
        if not math.isclose(self._gamma, other._gamma):
            raise ValueError(
                "cannot merge sketches with different relative accuracy "
                f"({self.relative_accuracy} vs {other.relative_accuracy})"
            )
        # Snapshot the donor first: taking both locks in caller order
        # could deadlock two concurrent a.merge(b) / b.merge(a).
        with other._lock:
            counts = dict(other._counts)
            zero, count = other._zero, other._count
            s, lo, hi = other._sum, other._min, other._max
        with self._lock:
            for idx, c in counts.items():
                self._counts[idx] = self._counts.get(idx, 0) + c
            self._zero += zero
            self._count += count
            self._sum += s
            self._min = min(self._min, lo)
            self._max = max(self._max, hi)
        return self

    # -- read -----------------------------------------------------------

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> Optional[float]:
        """The q-quantile (0 <= q <= 1) of everything observed, within
        the declared relative error; None on an empty sketch."""
        if not (0.0 <= q <= 1.0):
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            if self._count == 0:
                return None
            counts = sorted(self._counts.items())
            zero, total = self._zero, self._count
        rank = max(0, min(total - 1, int(math.ceil(q * total)) - 1))
        if rank < zero:
            return 0.0
        acc = zero
        for idx, c in counts:
            acc += c
            if rank < acc:
                return self._representative(idx)
        return self._representative(counts[-1][0]) if counts else 0.0

    def to_json(self) -> dict:
        with self._lock:
            return {
                "relative_accuracy": self.relative_accuracy,
                "counts": {str(k): v for k, v in sorted(self._counts.items())},
                "zero": self._zero,
                "count": self._count,
                "sum": self._sum,
                "min": None if self._count == 0 else self._min,
                "max": None if self._count == 0 else self._max,
            }

    @classmethod
    def from_json(cls, rec: dict) -> "LatencySketch":
        sketch = cls(rec.get("relative_accuracy", DEFAULT_RELATIVE_ACCURACY))
        sketch._counts = {int(k): int(v) for k, v in rec.get("counts", {}).items()}
        sketch._zero = int(rec.get("zero", 0))
        sketch._count = int(rec.get("count", 0))
        sketch._sum = float(rec.get("sum", 0.0))
        if sketch._count:
            sketch._min = float(rec.get("min", 0.0))
            sketch._max = float(rec.get("max", 0.0))
        return sketch


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective (module docstring). Exactly one signal
    source: `sketch` + `threshold_seconds` (a duration stream — good iff
    the value is under the threshold) or `event` (an explicit good/bad
    stream fed via :meth:`SLOEngine.event`)."""

    name: str
    objective: float
    description: str = ""
    #: duration metric this SLO watches (also feeds the named sketch).
    sketch: Optional[str] = None
    threshold_seconds: Optional[float] = None
    #: good/bad event stream name (error-rate / shed-rate SLOs).
    event: Optional[str] = None
    fast_window_seconds: float = 300.0
    fast_burn_threshold: float = 14.4
    slow_window_seconds: float = 3600.0
    slow_burn_threshold: float = 6.0
    #: below this many events in a window the burn rate reads 0 — a
    #: single bad request at dawn must not page anyone.
    min_events: int = 1
    #: a fast burn of this SLO drives admission degradation (the serve
    #: tier sheds lowest-priority work). Shed-rate SLOs set False:
    #: shedding to cure a shed-rate burn is a feedback loop.
    degrade: bool = True

    def __post_init__(self) -> None:
        if not (0.0 < self.objective < 1.0):
            raise ValueError(
                f"SLO {self.name!r}: objective must be in (0, 1), got "
                f"{self.objective}"
            )
        if (self.sketch is None) == (self.event is None):
            raise ValueError(
                f"SLO {self.name!r}: exactly one of sketch= or event= "
                "must be set"
            )
        if self.sketch is not None and self.threshold_seconds is None:
            raise ValueError(
                f"SLO {self.name!r}: a sketch-based SLO needs "
                "threshold_seconds"
            )
        if self.fast_window_seconds <= 0 or self.slow_window_seconds <= 0:
            raise ValueError(f"SLO {self.name!r}: windows must be > 0")
        if self.min_events < 1:
            raise ValueError(f"SLO {self.name!r}: min_events must be >= 1")

    @property
    def error_budget(self) -> float:
        return 1.0 - self.objective

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class _SpecState:
    """One SLO's live accounting: per-second (clock-bucketed) good/bad
    counts bounded by the slow window, plus the current alert state."""

    __slots__ = ("spec", "buckets", "state")

    def __init__(self, spec: SLOSpec):
        self.spec = spec
        self.buckets: list = []  # [sec, good, bad], append-ordered
        self.state = "ok"

    def record(self, now: float, good: bool) -> None:
        sec = int(now)
        if self.buckets and self.buckets[-1][0] == sec:
            b = self.buckets[-1]
        else:
            self.buckets.append([sec, 0, 0])
            b = self.buckets[-1]
        b[1 if good else 2] += 1
        self._trim(now)

    def _trim(self, now: float) -> None:
        horizon = int(now) - int(
            max(self.spec.slow_window_seconds, self.spec.fast_window_seconds)
        ) - 1
        while self.buckets and self.buckets[0][0] < horizon:
            self.buckets.pop(0)

    def window_counts(self, now: float, window_seconds: float) -> tuple:
        lo = now - window_seconds
        good = bad = 0
        for sec, g, b in self.buckets:
            if sec >= lo:
                good += g
                bad += b
        return good, bad

    def burn_rate(self, now: float, window_seconds: float) -> float:
        good, bad = self.window_counts(now, window_seconds)
        total = good + bad
        if total < self.spec.min_events or total == 0:
            return 0.0
        return (bad / total) / self.spec.error_budget


class SLOEngine:
    """The burn-rate evaluator (module docstring). Thread-safe; the
    clock is injectable so burn-rate arithmetic pins against
    hand-computed windows in tests. `on_transition` (when given) is
    called with each alert record OUTSIDE the engine lock — the serving
    tier appends them to its crash-safe request ledger."""

    #: alert history bound (oldest dropped): post-mortems need the
    #: recent story, not an unbounded list on a year-old process.
    MAX_ALERTS = 1000

    def __init__(
        self,
        specs: Sequence[SLOSpec] = (),
        *,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
        on_transition: Optional[Callable[[dict], None]] = None,
    ):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names in {names}")
        self.specs = tuple(specs)
        self._clock = clock
        self._lock = threading.Lock()
        self._states = {s.name: _SpecState(s) for s in specs}
        self._sketches: dict[str, LatencySketch] = {}
        self._alerts: list[dict] = []
        self.on_transition = on_transition
        if registry is None:
            from yuma_simulation_tpu.telemetry.metrics import get_registry

            registry = get_registry()
        self._alerts_total = registry.counter(
            "slo_alerts_total", help="SLO burn-rate alert transitions"
        )
        self._fast_gauge = registry.gauge(
            "slo_fast_burn_active", help="SLOs currently fast-burning"
        )
        self._slow_gauge = registry.gauge(
            "slo_slow_burn_active", help="SLOs currently slow-burning"
        )

    # -- ingest ---------------------------------------------------------

    def sketch(self, metric: str) -> LatencySketch:
        with self._lock:
            sk = self._sketches.get(metric)
            if sk is None:
                sk = self._sketches[metric] = LatencySketch()
            return sk

    def observe(self, metric: str, seconds: float) -> None:
        """One duration observation: feeds the named sketch, and every
        sketch-based SLO watching `metric` scores it good/bad against
        its threshold."""
        self.sketch(metric).observe(seconds)
        now = self._clock()
        transitions = []
        with self._lock:
            for st in self._states.values():
                if st.spec.sketch != metric:
                    continue
                st.record(now, float(seconds) <= st.spec.threshold_seconds)
                transitions.extend(self._evaluate_locked(st, now))
        self._emit(transitions)

    def event(self, metric: str, ok: bool) -> None:
        """One good/bad event for every event-based SLO on `metric`."""
        now = self._clock()
        transitions = []
        with self._lock:
            for st in self._states.values():
                if st.spec.event != metric:
                    continue
                st.record(now, bool(ok))
                transitions.extend(self._evaluate_locked(st, now))
        self._emit(transitions)

    # -- evaluation -----------------------------------------------------

    def _evaluate_locked(self, st: _SpecState, now: float) -> list[dict]:
        st._trim(now)
        fast = st.burn_rate(now, st.spec.fast_window_seconds)
        slow = st.burn_rate(now, st.spec.slow_window_seconds)
        if fast >= st.spec.fast_burn_threshold:
            new = "fast_burn"
            burn = fast
        elif slow >= st.spec.slow_burn_threshold:
            new = "slow_burn"
            burn = slow
        else:
            new = "ok"
            burn = max(fast, slow)
        if new == st.state:
            return []
        old, st.state = st.state, new
        record = {
            "t": round(time.time(), 6),
            "slo": st.spec.name,
            "from": old,
            "to": new,
            "burn_rate": round(burn, 4),
            "fast_burn_rate": round(fast, 4),
            "slow_burn_rate": round(slow, 4),
            "objective": st.spec.objective,
        }
        self._alerts.append(record)
        del self._alerts[: -self.MAX_ALERTS]
        self._fast_gauge.set(
            sum(1 for s in self._states.values() if s.state == "fast_burn")
        )
        self._slow_gauge.set(
            sum(1 for s in self._states.values() if s.state == "slow_burn")
        )
        self._alerts_total.inc()
        return [record]

    def _emit(self, transitions: list[dict]) -> None:
        for rec in transitions:
            log_event(
                logger,
                "slo_alert" if rec["to"] != "ok" else "slo_recovered",
                level=(
                    logging.WARNING if rec["to"] != "ok" else logging.INFO
                ),
                slo=rec["slo"],
                state=rec["to"],
                was=rec["from"],
                burn=f"{rec['burn_rate']:.2f}",
                objective=rec["objective"],
            )
            if self.on_transition is not None:
                try:
                    self.on_transition(rec)
                except Exception:
                    logger.warning(
                        "SLO transition hook failed", exc_info=True
                    )

    def evaluate(self) -> dict:
        """Re-evaluate every SLO at the current clock (pure time passage
        un-flips a recovered burn) and return per-SLO status dicts."""
        now = self._clock()
        transitions = []
        out: dict[str, dict] = {}
        with self._lock:
            for name, st in sorted(self._states.items()):
                transitions.extend(self._evaluate_locked(st, now))
                fast_g, fast_b = st.window_counts(
                    now, st.spec.fast_window_seconds
                )
                slow_g, slow_b = st.window_counts(
                    now, st.spec.slow_window_seconds
                )
                out[name] = {
                    "state": st.state,
                    "objective": st.spec.objective,
                    "fast_burn_rate": round(
                        st.burn_rate(now, st.spec.fast_window_seconds), 4
                    ),
                    "slow_burn_rate": round(
                        st.burn_rate(now, st.spec.slow_window_seconds), 4
                    ),
                    "fast_window": {"good": fast_g, "bad": fast_b},
                    "slow_window": {"good": slow_g, "bad": slow_b},
                    "degrade": st.spec.degrade,
                }
        self._emit(transitions)
        return out

    def state(self, name: str) -> str:
        self.evaluate()
        with self._lock:
            return self._states[name].state

    def fast_burning(self) -> tuple:
        """Names of SLOs currently fast-burning (evaluated now)."""
        status = self.evaluate()
        return tuple(
            name for name, s in status.items() if s["state"] == "fast_burn"
        )

    def degraded(self) -> tuple:
        """Fast-burning SLOs that drive admission degradation — the
        serving tier sheds lowest-priority work while this is
        non-empty."""
        status = self.evaluate()
        return tuple(
            name
            for name, s in status.items()
            if s["state"] == "fast_burn" and s["degrade"]
        )

    def alerts(self) -> list[dict]:
        with self._lock:
            return list(self._alerts)

    def snapshot(self) -> dict:
        """The full engine state for ``slo.json``/`/healthz`: specs,
        per-SLO status, sketches (serialized + headline quantiles),
        alert history."""
        status = self.evaluate()
        with self._lock:
            sketches = dict(self._sketches)
            alerts = list(self._alerts)
        sketch_out = {}
        for metric, sk in sorted(sketches.items()):
            rec = sk.to_json()
            rec["quantiles"] = {
                q: sk.quantile(float(q))
                for q in ("0.5", "0.9", "0.99")
            }
            sketch_out[metric] = rec
        return {
            "specs": [s.to_json() for s in self.specs],
            "states": status,
            "sketches": sketch_out,
            "alerts": alerts,
        }


# ------------------------------------------------------------ process state

#: The default objectives every process carries. Deliberately generous
#: (CI drills and CPU smoke runs must never trip them); a deployment
#: replaces them via :func:`set_slo_engine` or the serving tier's
#: ``slo_specs`` knob.
DEFAULT_SLO_SPECS = (
    SLOSpec(
        "serve_latency",
        objective=0.99,
        description="p99 serve request wall time under 30s",
        sketch="serve_request_seconds",
        threshold_seconds=30.0,
        fast_window_seconds=60.0,
        slow_window_seconds=600.0,
        min_events=20,
    ),
    SLOSpec(
        "serve_errors",
        objective=0.995,
        description="serve requests answered without a 5xx",
        event="serve_request_ok",
        fast_window_seconds=60.0,
        slow_window_seconds=600.0,
        min_events=20,
    ),
    SLOSpec(
        "serve_shed",
        objective=0.9,
        description="serve requests admitted (not 429-shed)",
        event="serve_admitted",
        fast_window_seconds=60.0,
        slow_window_seconds=600.0,
        min_events=20,
        degrade=False,
    ),
    SLOSpec(
        "unit_duration",
        objective=0.95,
        description="supervised sweep units under 300s wall",
        sketch="unit_seconds",
        threshold_seconds=300.0,
        fast_window_seconds=120.0,
        slow_window_seconds=1800.0,
        min_events=10,
        degrade=False,
    ),
    SLOSpec(
        "cold_start",
        objective=0.9,
        description="compile regions under 120s (cold-start cost)",
        sketch="compile_seconds",
        threshold_seconds=120.0,
        fast_window_seconds=300.0,
        slow_window_seconds=3600.0,
        min_events=10,
        degrade=False,
    ),
    # The numerics-drift objective (0.14.0): every cross-engine canary
    # re-execution must reproduce the primary's bits. min_events=1 by
    # design — a SINGLE confirmed drift is an incident, not noise (the
    # event stream only carries deliberate canary comparisons, never
    # request traffic), so one bad canary fast-burns, flips `/healthz`
    # to degraded, and fails `sloreport --check` until recovery.
    SLOSpec(
        "engine_drift",
        objective=0.999,
        description="cross-engine numerics canaries reproducing the "
        "primary's bits (telemetry.numerics)",
        event="engine_drift_ok",
        fast_window_seconds=60.0,
        slow_window_seconds=600.0,
        min_events=1,
        degrade=True,
    ),
    # The continuous-replay freshness objective (0.22.0): each controller
    # cycle feeds one good/bad verdict per live subnet — good iff the
    # subnet's oldest unswept archive suffix is younger than the
    # controller's freshness budget (`replay_staleness_seconds` is the
    # gauge twin). A killed controller or a wedged fleet host turns the
    # stream bad within one poll interval, fast-burns, and recovers once
    # restarted sweeps drain the backlog; `degrade=True` lets the serve
    # tier shed low-priority what-ifs while the burn is active
    # (backpressure: capacity goes to catching the replay tail up).
    # Burn thresholds are scaled to the 0.95 objective (budget 0.05):
    # the SRE-canon 14.4x would need a >144% bad fraction — impossible
    # — so fast burn fires at 10x (>=50% of live subnets stale, e.g.
    # every subnet after a controller kill) and slow at 4x (>=20%
    # persistently stale — a shed tier that never catches up).
    SLOSpec(
        "replay_freshness",
        objective=0.95,
        description="live subnets whose unswept archive suffix is "
        "younger than the controller's freshness budget",
        event="replay_fresh",
        fast_window_seconds=60.0,
        fast_burn_threshold=10.0,
        slow_window_seconds=600.0,
        slow_burn_threshold=4.0,
        min_events=5,
        degrade=True,
    ),
)

_ENGINE: Optional[SLOEngine] = None
_ENGINE_LOCK = threading.Lock()


def get_slo_engine() -> SLOEngine:
    """The process SLO engine (lazily built over
    :data:`DEFAULT_SLO_SPECS`) — what the supervisor, the sentinel, and
    the serving tier feed without plumbing."""
    global _ENGINE
    with _ENGINE_LOCK:
        if _ENGINE is None:
            _ENGINE = SLOEngine(DEFAULT_SLO_SPECS)
        return _ENGINE


def peek_slo_engine() -> Optional[SLOEngine]:
    """The process engine if one exists, WITHOUT creating it — the
    flight recorder's probe (a bundle from a process that never observed
    an SLO signal should not grow an slo.json of zeros)."""
    with _ENGINE_LOCK:
        return _ENGINE


def set_slo_engine(engine: Optional[SLOEngine]) -> Optional[SLOEngine]:
    """Swap the process engine (deployments with custom specs, tests
    with fake clocks); returns the previous one. ``None`` resets to
    lazy-default."""
    global _ENGINE
    with _ENGINE_LOCK:
        previous, _ENGINE = _ENGINE, engine
        return previous


def observe_duration(metric: str, seconds: float) -> None:
    """Feed one duration into the process engine (creating it on first
    use): the supervisor's per-unit wall time, the sentinel's compile
    wall time. Never raises — SLO accounting must not break the sweep
    it measures."""
    try:
        get_slo_engine().observe(metric, seconds)
    except Exception:
        logger.warning("SLO observation failed for %s", metric, exc_info=True)


def observe_event(metric: str, ok: bool) -> None:
    """Feed one good/bad event into the process engine — the numerics
    canary's ``engine_drift_ok`` stream (a drift-confirming comparison
    is the bad event). Same never-raises contract as
    :func:`observe_duration`."""
    try:
        get_slo_engine().event(metric, ok)
    except Exception:
        logger.warning("SLO event failed for %s", metric, exc_info=True)


# --------------------------------------------- dispatch timing sketches

#: Hard cardinality bound on (engine x bucket x backend) keys: far past
#: any real serving mix (the canary LRU keeps 32 shapes), tight enough
#: that a hostile shape-per-request client cannot grow process memory.
MAX_DISPATCH_KEYS = 64

#: The fold-in key once the bound is hit — measured time is never
#: dropped, it just loses per-shape attribution past the bound.
DISPATCH_OVERFLOW_KEY = "overflow"


class _DispatchEntry:
    __slots__ = ("engine", "bucket", "backend", "sketch", "dispatches",
                 "epochs_total", "seconds_total")

    def __init__(self, engine: str, bucket: str, backend: str):
        self.engine = engine
        self.bucket = bucket
        self.backend = backend
        self.sketch = LatencySketch()
        self.dispatches = 0
        self.epochs_total = 0
        self.seconds_total = 0.0


class DispatchStats:
    """Always-on per-(engine rung x shape bucket x backend) dispatch
    timing: a :class:`LatencySketch` of wall seconds plus epoch/second
    totals per key, bounded at ``max_keys`` (the overflow key absorbs
    the tail). Fed host-side at the dispatch seam (one observe per
    dispatched region — O(1), no device sync of its own); snapshots
    ride flight-bundle metrics lines as the ``dispatch_sketches``
    field, which ``tools/perfattrib.py`` joins against the bundle's
    cost/roofline records into the measured-vs-predicted table."""

    def __init__(self, max_keys: int = MAX_DISPATCH_KEYS):
        self.max_keys = int(max_keys)
        self._lock = threading.Lock()
        self._entries: dict[str, _DispatchEntry] = {}

    @staticmethod
    def key_for(engine: str, bucket: str, backend: str) -> str:
        return f"{engine}|{bucket}|{backend}"

    def observe(
        self,
        *,
        engine: str,
        bucket: str,
        backend: str,
        seconds: float,
        epochs: int = 0,
    ) -> None:
        key = self.key_for(engine, bucket, backend)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                if len(self._entries) >= self.max_keys:
                    key = DISPATCH_OVERFLOW_KEY
                    entry = self._entries.get(key)
                    if entry is None:
                        entry = self._entries[key] = _DispatchEntry(
                            DISPATCH_OVERFLOW_KEY, "", ""
                        )
                else:
                    entry = self._entries[key] = _DispatchEntry(
                        engine, bucket, backend
                    )
            entry.dispatches += 1
            entry.epochs_total += int(epochs)
            entry.seconds_total += float(seconds)
        entry.sketch.observe(seconds)

    def snapshot(self) -> dict:
        """``{key: {engine, bucket, backend, dispatches, epochs_total,
        seconds_total, sketch}}`` — sketches serialized
        (:meth:`LatencySketch.to_json`), so snapshots merge exactly
        after the fact. Cumulative over process life: a consumer
        reading a snapshot stream keeps the highest-count line per
        key."""
        with self._lock:
            entries = dict(self._entries)
        out = {}
        for key, e in sorted(entries.items()):
            out[key] = {
                "engine": e.engine,
                "bucket": e.bucket,
                "backend": e.backend,
                "dispatches": e.dispatches,
                "epochs_total": e.epochs_total,
                "seconds_total": round(e.seconds_total, 6),
                "sketch": e.sketch.to_json(),
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


_DISPATCH_STATS = DispatchStats()

#: Process-wide kill switch for the dispatch-timing seam. Exists for
#: exactly one honest measurement: bench.py times the same dispatch
#: path observation-on vs observation-off to put a number on the
#: seam's own cost (perfgate gates `dispatch_sketch.overhead_frac`).
#: Production code never flips it.
_OBSERVE_ENABLED = True


def set_dispatch_observation(enabled: bool) -> bool:
    """Enable/disable :func:`observe_dispatch` process-wide; returns
    the previous setting so callers can restore it."""
    global _OBSERVE_ENABLED
    prev = _OBSERVE_ENABLED
    _OBSERVE_ENABLED = bool(enabled)
    return prev


def get_dispatch_stats() -> DispatchStats:
    """The process-wide dispatch timing table (see
    :class:`DispatchStats`)."""
    return _DISPATCH_STATS


def observe_dispatch(
    *,
    engine: str,
    bucket: str,
    backend: str,
    seconds: float,
    epochs: int = 0,
) -> None:
    """Feed one dispatched region's wall time into the process table.
    Host-side only, never raises — the measurement must not fail the
    dispatch it measures."""
    if not _OBSERVE_ENABLED:
        return
    try:
        _DISPATCH_STATS.observe(
            engine=engine,
            bucket=bucket,
            backend=backend,
            seconds=seconds,
            epochs=epochs,
        )
    except Exception:
        logger.warning(
            "dispatch timing observation failed for %s", engine,
            exc_info=True,
        )


def dispatch_snapshot() -> dict:
    """The process dispatch table, serialized ({} when nothing has
    dispatched) — what flight-bundle metrics lines carry as
    ``dispatch_sketches``."""
    try:
        return _DISPATCH_STATS.snapshot()
    except Exception:
        logger.warning("dispatch sketch snapshot failed", exc_info=True)
        return {}
