"""Bounded time-series store over the metric snapshot stream.

The flight bundle already persists everything a time series needs: each
``metrics.jsonl`` line is one cumulative registry snapshot (counters /
gauges / histograms + the ``dispatch_sketches`` meta), stamped with a
wall clock ``t`` and — since 0.24.0 — a monotone per-process ``seq``.
This module is the READ side: fold those lines into bounded per-key
rings of ``(t, seq, value)`` samples that the anomaly detectors
(:mod:`.anomaly`) scan. There is deliberately no new on-disk sink —
the snapshot stream IS the persistence, so the store rebuilds
identically from a live registry feed, a monolithic bundle, or any
merge of rotated segments.

Order independence: samples are deduplicated by ``(source, seq)`` (the
snapshot's producing process x its monotone counter) and read back
sorted by ``(t, seq)``, so ingesting router/worker/controller bundles
in any interleaving yields the same series. Pre-0.24.0 records without
``seq`` fall back to identity by ``(source, t)`` — cumulative snapshots
make a dropped duplicate harmless.

Series keys are namespaced by signal family:

- ``counter:<name>`` / ``gauge:<name>`` — registry scalars
  (epoch rates, queue depth, shed/reroute counters,
  ``replay_staleness_seconds``, ...);
- ``sketch:<key>:p50`` / ``sketch:<key>:p99`` — headline quantiles of
  each dispatch :class:`..slo.LatencySketch` entry riding the
  snapshot's ``dispatch_sketches`` meta.

Everything here is host-side plain Python: zero compiles, no reads
from traced code.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Iterable, Optional

#: Default per-key ring capacity. Soak-scale runs snapshot once per
#: controller cycle (~1/s), so 512 samples is minutes of history —
#: far beyond any detector window — at a few KiB per key.
DEFAULT_CAPACITY = 512

def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) and \
        math.isfinite(float(v))


class TimeSeriesStore:
    """Bounded per-key rings of ``(t, seq, value)`` samples folded from
    metric snapshot records (live or bundle-loaded)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        self.capacity = int(capacity)
        self._series: dict[str, deque] = {}
        self._seen: set[tuple] = set()
        #: bounds the dedupe set alongside the rings.
        self._seen_order: deque = deque()

    # -- ingest ----------------------------------------------------------

    def ingest_snapshot(self, record: dict, *, source: str = "") -> bool:
        """Fold one ``metrics.jsonl``-shaped snapshot record into the
        rings. Returns False (a no-op) when the ``(source, seq)``
        identity was already ingested — merge-replay safe."""
        if not isinstance(record, dict):
            return False
        t = record.get("t")
        if not _is_number(t):
            return False
        seq = record.get("seq")
        src = source or str(record.get("source") or record.get("run_id") or "")
        ident = (src, int(seq)) if _is_number(seq) else (src, float(t))
        if ident in self._seen:
            return False
        self._seen.add(ident)
        self._seen_order.append(ident)
        # Bound the identity set: capacity samples per seen key is the
        # most the rings retain, so remembering ~8x that many identities
        # keeps replay-dedupe exact for everything still in a ring.
        max_seen = self.capacity * 8
        while len(self._seen_order) > max_seen:
            self._seen.discard(self._seen_order.popleft())
        order = float(seq) if _is_number(seq) else float(t)
        for family in ("counters", "gauges"):
            block = record.get(family)
            if not isinstance(block, dict):
                continue
            prefix = "counter:" if family == "counters" else "gauge:"
            for name, value in block.items():
                if _is_number(value):
                    self._push(prefix + str(name), float(t), order,
                               float(value))
        sketches = record.get("dispatch_sketches")
        if isinstance(sketches, dict):
            self._ingest_sketches(sketches, float(t), order)
        return True

    def _ingest_sketches(self, sketches: dict, t: float, order: float) -> None:
        from yuma_simulation_tpu.telemetry.slo import LatencySketch

        for key, entry in sketches.items():
            if not isinstance(entry, dict):
                continue
            rec = entry.get("sketch")
            if not isinstance(rec, dict):
                continue
            try:
                sk = LatencySketch.from_json(rec)
                p50 = sk.quantile(0.5)
                p99 = sk.quantile(0.99)
            except Exception:
                continue
            if p50 is not None:
                self._push(f"sketch:{key}:p50", t, order, float(p50))
            if p99 is not None:
                self._push(f"sketch:{key}:p99", t, order, float(p99))

    def _push(self, key: str, t: float, order: float, value: float) -> None:
        ring = self._series.get(key)
        if ring is None:
            ring = deque(maxlen=self.capacity)
            self._series[key] = ring
        ring.append((t, order, value))

    def ingest_many(self, records: Iterable[dict], *,
                    source: str = "") -> int:
        """Fold a batch of snapshot records; returns how many were new."""
        return sum(
            1 for r in records if self.ingest_snapshot(r, source=source)
        )

    # -- read ------------------------------------------------------------

    def keys(self) -> tuple:
        return tuple(sorted(self._series))

    def series(self, key: str) -> tuple:
        """``((t, value), ...)`` for `key`, sorted by ``(t, seq)`` —
        the order-independent read surface."""
        return tuple((t, v) for t, _order, v in self.samples(key))

    def samples(self, key: str) -> tuple:
        """``((t, order, value), ...)`` for `key`, sorted by
        ``(t, order)``. The read surface for stateful scanners: a ring
        EVICTS once full, so an index into :meth:`series` stops
        advancing the moment old samples fall off — ``(t, order)`` is a
        per-sample identity a cursor can compare against instead."""
        ring = self._series.get(key)
        if not ring:
            return ()
        return tuple(sorted(ring, key=lambda s: (s[0], s[1])))

    def latest(self, key: str) -> Optional[tuple]:
        s = self.series(key)
        return s[-1] if s else None

    def __len__(self) -> int:
        return len(self._series)


def store_from_metrics(
    records: Iterable[dict],
    *,
    capacity: int = DEFAULT_CAPACITY,
    source: str = "",
) -> TimeSeriesStore:
    """Rebuild a store from bundle ``metrics`` records (the offline
    twin of the live feed): ``store_from_metrics(load_bundle(d).metrics)``."""
    store = TimeSeriesStore(capacity=capacity)
    store.ingest_many(records, source=source)
    return store
