"""Run-scoped tracing: one `RunContext` per sweep, nested `span` timers.

The resilience tier (PRs 1-3) emits one structured record per recovery
action, but the records carry no run identity: two concurrent sweeps —
or a sweep and its later resume — interleave indistinguishably in the
log stream and the :class:`..resilience.supervisor.FailureLedger`. This
module provides the identity substrate:

- :class:`RunContext` mints a process-unique ``run_id`` and collects the
  run's closed spans; it is installed in a :mod:`contextvars` context
  variable, so nested libraries need no plumbing to find it;
- :func:`span` opens one named, timed span under the innermost open span
  (sweep -> unit -> attempt -> engine rung is the supervisor's chain);
  span records carry ``span_id`` / ``parent_id`` and land on the owning
  run at close;
- :func:`current_fields` returns the ``{run_id, span_id, parent_id}``
  mapping that :func:`..utils.logging.log_event` and
  ``FailureLedger.append`` stamp into every record they emit — the join
  key between the log stream, the ledger, and the span tree;
- :func:`dispatch_annotation` wraps a host-level engine dispatch in
  ``jax.profiler.StepTraceAnnotation`` with a process-monotonic step
  number (recorded on the open span), so a Perfetto trace's step lanes
  line up with the ledger's span ids.

Host-level ONLY, by construction: everything here is wall-clock + dict
bookkeeping on the Python side of a dispatch. Nothing touches traced
values, and :func:`dispatch_annotation` self-guards with the same
is-tracing check as the fault hooks (a `shard_map` body re-enters
`simulate_batch` at trace time; annotating a trace would be noise and
the step counter an impurity baked into nothing useful). The telemetry
layer therefore adds zero compiles — pinned by
tests/unit/test_recompilation.py's existing zero budgets.

Thread note: `contextvars` do NOT flow into a bare `threading.Thread`;
the deadline watchdog (the one place this framework hops threads)
copies the caller's context into its worker explicitly, so records
emitted from a supervised dispatch carry the caller's run/span identity.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Iterator, Optional

_CURRENT_RUN: contextvars.ContextVar[Optional["RunContext"]] = (
    contextvars.ContextVar("yuma_telemetry_run", default=None)
)
_CURRENT_SPAN: contextvars.ContextVar[Optional["Span"]] = (
    contextvars.ContextVar("yuma_telemetry_span", default=None)
)

#: Process-monotonic dispatch step counter for
#: :func:`dispatch_annotation` (itertools.count is atomic in CPython).
_DISPATCH_STEP = itertools.count()


def new_run_id() -> str:
    """A process-unique, human-greppable run identifier."""
    return "run-" + uuid.uuid4().hex[:12]


def _tracing_now() -> bool:
    """Whether a jax trace is executing this host code (same fail-closed
    probe as :mod:`..resilience.faults`)."""
    try:
        from jax import core

        return not core.trace_state_clean()
    except Exception:
        return True


@dataclass
class Span:
    """One closed-interval timer in a run's span tree. ``parent_id`` is
    empty for a root span. Times are wall-clock (`time.time()`) so the
    flight recorder's timeline is human-readable; durations at this
    layer are unit/attempt scale (ms and up), not kernel scale."""

    span_id: str
    parent_id: str
    name: str
    t_start: float
    t_end: Optional[float] = None
    status: str = "ok"
    #: host-side annotations (e.g. the profiler step numbers of the
    #: dispatches issued under this span) — flat JSON-able values only.
    attrs: dict = field(default_factory=dict)
    #: ``parent_id`` lives in ANOTHER process's bundle (a continued
    #: cross-process trace, :mod:`.propagation`): the single-bundle
    #: consistency check must not demand local resolution, and the
    #: stitched multi-bundle check must demand sibling resolution.
    remote: bool = False

    def to_record(self, run_id: str) -> dict:
        rec = {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "run_id": run_id,
            "t_start": round(self.t_start, 6),
            "t_end": None if self.t_end is None else round(self.t_end, 6),
            "status": self.status,
        }
        if self.remote:
            rec["remote_parent"] = True
        if self.attrs:
            rec["attrs"] = dict(self.attrs)
        return rec


class RunContext:
    """The identity scope for one run (a sweep, a CLI invocation, a
    bench). Enter it as a context manager; everything executed inside —
    any thread the watchdog copies the context into included — stamps
    this ``run_id`` on its records.

    Span ids are minted per run (`s0001`, `s0002`, ...) under a lock, so
    a span tree is readable in ledger order and safe to grow from the
    watchdog's worker threads.

    Cross-process continuation (:mod:`.propagation`): a child process
    joining an upstream trace passes the caller's ``run_id`` plus a
    process-unique ``span_prefix`` (ids become ``<prefix>.s0001`` so two
    processes minting spans in one run can never collide) and the
    caller's span as ``remote_parent`` — every span this context opens
    with no LOCAL parent roots under the caller's span instead of
    floating as an orphan.
    """

    def __init__(
        self,
        run_id: Optional[str] = None,
        *,
        span_prefix: str = "",
        remote_parent: str = "",
    ):
        self.run_id = run_id if run_id else new_run_id()
        if span_prefix and ("-" in span_prefix or " " in span_prefix):
            # Span ids must survive the traceparent header's dash-split
            # framing (propagation.TraceContext) and log tokenization.
            raise ValueError(
                f"span_prefix {span_prefix!r} must not contain '-' or spaces"
            )
        self.span_prefix = span_prefix
        self.remote_parent = remote_parent
        self.t_start = time.time()
        self._lock = threading.Lock()
        self._next = itertools.count(1)
        self._closed: list[Span] = []
        self._open: dict[str, Span] = {}
        self._token: Optional[contextvars.Token] = None

    # -- context management --------------------------------------------

    def __enter__(self) -> "RunContext":
        if self._token is not None:
            raise RuntimeError(f"RunContext {self.run_id} already entered")
        self._token = _CURRENT_RUN.set(self)
        return self

    def __exit__(self, *exc) -> None:
        assert self._token is not None
        _CURRENT_RUN.reset(self._token)
        self._token = None

    @contextlib.contextmanager
    def activate(self) -> Iterator["RunContext"]:
        """Join this run from ANY thread, concurrently. Unlike
        ``__enter__`` (exclusive — one entry, the owning scope),
        ``activate()`` may be held by many threads at once: each thread
        gets its own contextvar binding, so a long-lived service can
        stamp every request-handler thread's spans/records with ONE
        server run without serializing the handlers. Span bookkeeping
        is lock-protected, so concurrent activations are safe."""
        token = _CURRENT_RUN.set(self)
        try:
            yield self
        finally:
            _CURRENT_RUN.reset(token)

    # -- span bookkeeping (called by :func:`span`) ---------------------

    def _mint_span_id(self) -> str:
        sid = f"s{next(self._next):04d}"
        return f"{self.span_prefix}.{sid}" if self.span_prefix else sid

    def _open_span(self, name: str, parent: Optional[Span]) -> Span:
        if parent is not None:
            parent_id, remote = parent.span_id, False
        else:
            parent_id, remote = self.remote_parent, bool(self.remote_parent)
        s = Span(
            span_id="",
            parent_id=parent_id,
            name=name,
            t_start=time.time(),
            remote=remote,
        )
        with self._lock:
            s.span_id = self._mint_span_id()
            self._open[s.span_id] = s
        return s

    def record_span(
        self,
        name: str,
        t_start: float,
        t_end: float,
        *,
        parent_id: str = "",
        status: str = "ok",
        **attrs,
    ) -> Span:
        """Append one ALREADY-CLOSED span with explicit wall-clock
        bounds — how the serving tier reconstructs a request's critical
        path (queue wait, coalesce wait, execute) from timestamps taken
        on other threads, after the fact. ``parent_id`` defaults to a
        root (or the run's remote parent when continuing a trace)."""
        if not parent_id and self.remote_parent:
            parent_id, remote = self.remote_parent, True
        else:
            remote = False
        s = Span(
            span_id="",
            parent_id=parent_id,
            name=name,
            t_start=float(t_start),
            t_end=float(t_end),
            status=status,
            remote=remote,
        )
        if attrs:
            s.attrs.update(attrs)
        with self._lock:
            s.span_id = self._mint_span_id()
            self._closed.append(s)
        return s

    def _close_span(self, s: Span) -> None:
        s.t_end = time.time()
        with self._lock:
            self._open.pop(s.span_id, None)
            self._closed.append(s)

    def span_records(self) -> list[dict]:
        """All spans of this run as flat dicts: closed spans in close
        order, then any still-OPEN spans (serialized with
        ``status="open"`` and no ``t_end``). Open ancestors must be
        included because the flight recorder publishes mid-run — the
        supervisor's ``finally`` fires while an operator-opened outer
        span is still live, and a bundle whose sweep span references an
        unrecorded parent would fail its own ``obsreport --check``
        (:func:`..flight.FlightRecorder.record` replaces the open
        record with the closed form on a later publish)."""
        with self._lock:
            records = [s.to_record(self.run_id) for s in self._closed]
            open_spans = sorted(
                self._open.values(), key=lambda s: s.t_start
            )
        for s in open_spans:
            rec = s.to_record(self.run_id)
            if rec["status"] == "ok":
                rec["status"] = "open"
            records.append(rec)
        return records


def current_run() -> Optional[RunContext]:
    """The innermost active :class:`RunContext`, or None."""
    return _CURRENT_RUN.get()


def current_span() -> Optional[Span]:
    """The innermost OPEN span, or None."""
    return _CURRENT_SPAN.get()


def current_fields() -> dict:
    """The identity fields every telemetry-aware record carries:
    ``{"run_id": ...}`` plus ``span_id``/``parent_id`` when a span is
    open. Empty dict when no run is active — the zero-overhead
    production-off state (one ContextVar read)."""
    run = _CURRENT_RUN.get()
    if run is None:
        return {}
    fields = {"run_id": run.run_id}
    s = _CURRENT_SPAN.get()
    if s is not None:
        fields["span_id"] = s.span_id
        if s.parent_id:
            fields["parent_id"] = s.parent_id
    return fields


@contextlib.contextmanager
def span(
    name: str, *, root: bool = False, **attrs
) -> Iterator[Optional[Span]]:
    """Open one named span under the innermost open span of the active
    run. No active run -> a no-op yielding None (library code can span
    unconditionally). An exception inside the span marks it
    ``status="error"`` and propagates; the span always closes.

    ``root=True`` detaches from the caller's innermost span and opens
    directly under the run's root (or its remote parent) — for records
    emitted ON one run from a thread whose innermost span belongs to a
    DIFFERENT run (e.g. an SLO transition fired mid-request of a
    continued trace), where inheriting the foreign span would record an
    unresolvable parent."""
    run = _CURRENT_RUN.get()
    if run is None:
        yield None
        return
    s = run._open_span(name, None if root else _CURRENT_SPAN.get())
    if attrs:
        s.attrs.update(attrs)
    token = _CURRENT_SPAN.set(s)
    try:
        yield s
    except BaseException:
        s.status = "error"
        raise
    finally:
        _CURRENT_SPAN.reset(token)
        run._close_span(s)


@contextlib.contextmanager
def ensure_run(run_id: Optional[str] = None) -> Iterator[RunContext]:
    """The active run, or a fresh one entered for the duration of the
    block — how the supervisor joins an operator-opened CLI run instead
    of forking a second run_id for the same work."""
    run = _CURRENT_RUN.get()
    if run is not None:
        yield run
        return
    with RunContext(run_id) as run:
        yield run


@contextlib.contextmanager
def dispatch_annotation(name: str) -> Iterator[None]:
    """Wrap one host-level engine dispatch in a
    ``jax.profiler.StepTraceAnnotation`` with a process-monotonic step
    number, so Perfetto step lanes join against the span tree (the step
    number is appended to the open span's ``steps`` attr). Inert when a
    trace is executing (the `shard_map` body calls `simulate_batch` at
    trace time) and when the profiler is unavailable."""
    if _tracing_now():
        yield
        return
    step = next(_DISPATCH_STEP)
    s = _CURRENT_SPAN.get()
    if s is not None:
        s.attrs.setdefault("steps", []).append(step)
    try:
        import jax.profiler

        cm = jax.profiler.StepTraceAnnotation(name, step_num=step)
    except Exception:
        cm = contextlib.nullcontext()
    with cm:
        yield
