"""Telemetry layer: run-scoped tracing, metrics, device/compile
telemetry, and the sweep flight recorder.

The observability substrate under the resilience tier (SURVEY.md §5:
the reference has bare prints; PRs 1-3 added recovery but no identity
or rates). The modules:

- :mod:`.cost` — the compile-time half: AOT cost/memory capture per
  engine rung (``cost_analysis``/``memory_analysis`` + HLO
  fingerprint), the roofline estimator over an overridable
  :class:`~.cost.DeviceSpec` table, and the analytic HBM preflight the
  engine/sharding advisors run before every dispatch;
- :mod:`.runctx` — `RunContext` + nested `span` timers; every
  `log_event` record and `FailureLedger` line is stamped with
  ``run_id``/``span_id``, and `dispatch_annotation` lines Perfetto
  traces up with the span tree;
- :mod:`.metrics` — the process-local counters/gauges/histograms
  registry with JSONL snapshot and Prometheus text sinks;
- :mod:`.device` — HBM/live-buffer/jit-cache sampling at span
  boundaries (graceful None on CPU);
- :mod:`.flight` — the per-run on-disk bundle (ledger + spans +
  metrics + report + SLO state) and its loader/consistency checks —
  the single-bundle AND stitched multi-bundle orphan gates — rendered
  by ``python -m tools.obsreport``;
- :mod:`.propagation` — cross-process trace continuation: the
  serializable `TraceContext` that rides HTTP headers, fleet manifests,
  lease records and subprocess environments so serve -> supervisor ->
  fleet is ONE trace;
- :mod:`.slo` — mergeable log-bucketed latency sketches, declarative
  `SLOSpec` objectives, and the burn-rate engine whose fast-burn
  alerts drive the serving tier's admission degradation
  (``python -m tools.sloreport`` renders and gates the state);
- :mod:`.timeseries` / :mod:`.anomaly` / :mod:`.incident` — incident
  intelligence (0.24.0): bounded per-key time-series rings folded from
  the metric snapshot stream, robust anomaly detectors (MAD,
  rate-of-change, counter-stall, saturation), and the correlation
  engine that joins anomalies, SLO transitions, and typed fault ledger
  events into durable ``incidents.jsonl`` postmortem records
  (``python -m tools.incidentreport`` renders and gates them).

Everything is host-side: the layer adds zero compiles (the warm-repeat
budgets of tests/unit/test_recompilation.py stay at 0) and no reads
from inside traced code.
"""

from yuma_simulation_tpu.telemetry.cost import (  # noqa: F401
    DEVICE_SPECS,
    ENGINE_RUNGS,
    CostRecord,
    DeviceSpec,
    FootprintEstimate,
    HBMPreflightError,
    PreflightVerdict,
    Roofline,
    capture_compiled,
    capture_engine_cost,
    capture_engine_costs,
    estimate_hbm_bytes,
    preflight_hbm,
    resolve_device_spec,
    roofline,
)
from yuma_simulation_tpu.telemetry.device import (  # noqa: F401
    CompileTracker,
    record_device_telemetry,
    sample_device_telemetry,
)
from yuma_simulation_tpu.telemetry.anomaly import (  # noqa: F401
    Anomaly,
    AnomalyEngine,
    CounterStallDetector,
    MadDetector,
    RateOfChangeDetector,
    SaturationDetector,
)
from yuma_simulation_tpu.telemetry.flight import (  # noqa: F401
    Bundle,
    FlightRecorder,
    build_timeline,
    check_bundle,
    check_stitched,
    ledger_counts,
    load_bundle,
    merge_bundles,
)
from yuma_simulation_tpu.telemetry.incident import (  # noqa: F401
    CAUSE_EVENTS,
    Incident,
    IncidentEngine,
    correlate,
    correlate_bundle,
    latest_incidents,
    load_incidents,
    open_incident_count,
)
from yuma_simulation_tpu.telemetry.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    record_epoch_rate,
)
from yuma_simulation_tpu.telemetry.propagation import (  # noqa: F401
    TraceContext,
    child_run,
    continue_trace,
    current_trace_context,
    span_prefix_for,
)
from yuma_simulation_tpu.telemetry.runctx import (  # noqa: F401
    RunContext,
    Span,
    current_fields,
    current_run,
    current_span,
    dispatch_annotation,
    ensure_run,
    new_run_id,
    span,
)
from yuma_simulation_tpu.telemetry.slo import (  # noqa: F401
    DEFAULT_SLO_SPECS,
    LatencySketch,
    SLOEngine,
    SLOSpec,
    get_slo_engine,
    observe_duration,
    set_slo_engine,
)
from yuma_simulation_tpu.telemetry.timeseries import (  # noqa: F401
    TimeSeriesStore,
    store_from_metrics,
)
