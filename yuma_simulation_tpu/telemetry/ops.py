"""Live operations plane for standing hosts (PR 19).

Every long-lived process in the system — the serve tier, the pool
workers, the replay controller, the fleet host — already *has* the
observability substrate (metrics registry, SLO engine, flight
recorder, dispatch sketches). What it lacked was a way to look at any
of it **while the process is alive** without killing it and reading
the bundle. This module is that seam, deliberately transport-free so
the HTTP layer (:mod:`..serve.server`), the controller and the fleet
host can all mount the same three surfaces:

- :meth:`OpsPlane.debug_vars` — one JSON snapshot of the process:
  metrics registry counters/gauges/histograms, SLO evaluation + burn
  states, the bounded dispatch-sketch table, the recent structured
  events ring, profiler status, and the flight-segment summary when
  rotation is on. Pure reads under short locks; never blocks dispatch.
- :meth:`OpsPlane.debug_spans` — one run's span tree, stitched from
  the sealed bundle on disk *plus* the live in-memory
  :class:`.runctx.RunContext` (spans the recorder hasn't flushed yet),
  rendered through the same :func:`.flight.build_timeline` obsreport
  uses so live and post-hoc views can never diverge structurally.
- :meth:`OpsPlane.debug_profile` — guarded on-demand device
  profiling: a single-flight latch around ``jax.profiler`` (the
  profiler is a process singleton; two overlapping traces corrupt
  both), an auto-stop deadline timer so an operator who walks away
  cannot leave the profiler running forever, and publication of the
  finished trace directory into the flight bundle
  (:meth:`.flight.FlightRecorder.record_profile`) so the artifact is
  discoverable from the bundle, not just a loose directory. A second
  request while one is in flight raises the typed
  :class:`ProfileBusyError` (the HTTP layer maps it to 409).

The **events ring**: :func:`.logging.log_event` — already the single
funnel for every structured recovery/lifecycle record in the package —
additionally appends each record here (bounded deque, process-global),
so ``GET /debug/vars`` shows the last ~256 events without any host
having to plumb a logger handler.
"""

from __future__ import annotations

import collections
import logging
import pathlib
import threading
import time
from typing import Optional, Union

logger = logging.getLogger(__name__)

#: Bound on the recent-events ring: big enough to cover a burst of
#: recovery records, small enough that /debug/vars stays one screenful.
EVENTS_RING_SIZE = 256

#: Hard ceiling on one profile window: the auto-stop deadline clamps
#: here even if the caller asks for more (a trace this long is an
#: operator error, not a use case).
MAX_PROFILE_SECONDS = 300.0

#: Profiling modes accepted by :meth:`ProfileSession.start`.
PROFILE_MODES = ("trace", "memory")


# -- recent-events ring ------------------------------------------------


class _EventsRing:
    """Process-global bounded ring of structured log records."""

    def __init__(self, maxlen: int = EVENTS_RING_SIZE):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=maxlen)

    def note(self, event: str, fields: dict) -> None:
        rec = {"event": str(event), "t": round(time.time(), 6)}
        for key, value in fields.items():
            if key not in rec:
                rec[key] = value if isinstance(
                    value, (int, float, bool)
                ) else str(value)
        with self._lock:
            self._ring.append(rec)

    def recent(self, limit: int = 64) -> list:
        with self._lock:
            items = list(self._ring)
        if limit > 0:
            items = items[-int(limit):]
        return items

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_EVENTS = _EventsRing()


def note_event(event: str, fields: dict) -> None:
    """Append one structured record to the process ring (called by
    :func:`..utils.logging.log_event` under its containment wrapper —
    this function must stay cheap and non-raising under the GIL)."""
    _EVENTS.note(event, fields)


def recent_events(limit: int = 64) -> list:
    """The newest ``limit`` structured records, oldest first."""
    return _EVENTS.recent(limit)


def clear_events() -> None:
    """Test hook: empty the ring (process-global state)."""
    _EVENTS.clear()


# -- on-demand device profiling ---------------------------------------


class ProfileBusyError(RuntimeError):
    """A profile window is already in flight (the profiler is a
    process singleton — overlapping traces corrupt both). Carries the
    live session status for the HTTP 409 body."""

    def __init__(self, status: dict):
        super().__init__(
            "a profile window is already active "
            f"(mode={status.get('mode')!r}, "
            f"deadline_t={status.get('deadline_t')})"
        )
        self.status = dict(status)


class ProfileSession:
    """Single-flight guard around ``jax.profiler`` with an auto-stop
    deadline and bundle registration.

    ``mode="trace"`` opens ``jax.profiler.start_trace`` into a fresh
    ``profiles/trace_NNN_<ts>`` directory under the bundle and arms a
    :class:`threading.Timer` for ``seconds``; :meth:`stop` (operator or
    timer, whichever first — idempotent under the latch) closes the
    trace and appends a ``profile_published`` record to the bundle's
    ``profiles.jsonl``. ``mode="memory"`` is synchronous: one device
    memory snapshot (``jax.profiler.save_device_memory_profile``),
    published immediately, never holds the latch across a window."""

    def __init__(self, bundle_dir: Optional[Union[str, pathlib.Path]]):
        self.bundle_dir = (
            pathlib.Path(bundle_dir) if bundle_dir is not None else None
        )
        self._lock = threading.Lock()
        self._active: Optional[dict] = None
        self._timer: Optional[threading.Timer] = None
        self._serial = 0
        self._published = 0

    # -- introspection -------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            active = dict(self._active) if self._active else None
        out = {
            "active": active is not None,
            "profiles_published": self._published,
        }
        if active:
            out.update(active)
        return out

    # -- lifecycle -----------------------------------------------------

    def _profiles_root(self) -> pathlib.Path:
        if self.bundle_dir is None:
            raise ValueError(
                "on-demand profiling requires a bundle directory "
                "(the trace artifact must register somewhere)"
            )
        root = self.bundle_dir / "profiles"
        root.mkdir(parents=True, exist_ok=True)
        return root

    def start(self, seconds: float, mode: str = "trace") -> dict:
        """Begin one profile window. Raises :class:`ProfileBusyError`
        when a window is already active, :class:`ValueError` on an
        unknown mode, a non-positive duration, or a host with no
        bundle directory."""
        if mode not in PROFILE_MODES:
            raise ValueError(
                f"unknown profile mode {mode!r} "
                f"(expected one of {PROFILE_MODES})"
            )
        seconds = float(seconds)
        if not seconds > 0:
            raise ValueError(f"profile seconds must be > 0, got {seconds}")
        seconds = min(seconds, MAX_PROFILE_SECONDS)
        with self._lock:
            if self._active is not None:
                raise ProfileBusyError(dict(self._active))
            self._serial += 1
            serial = self._serial
            stamp = int(time.time())
            if mode == "memory":
                # Synchronous one-shot: never holds the latch open.
                path = self._profiles_root() / (
                    f"memory_{serial:03d}_{stamp}.prof"
                )
                import jax

                jax.profiler.save_device_memory_profile(str(path))
                return self._publish(
                    {
                        "mode": "memory",
                        "serial": serial,
                        "artifact": str(path),
                        "seconds": 0.0,
                    }
                )
            trace_dir = self._profiles_root() / (
                f"trace_{serial:03d}_{stamp}"
            )
            import jax

            jax.profiler.start_trace(str(trace_dir))
            self._active = {
                "mode": "trace",
                "serial": serial,
                "artifact": str(trace_dir),
                "seconds": seconds,
                "t_started": round(time.time(), 6),
                "deadline_t": round(time.time() + seconds, 6),
            }
            self._timer = threading.Timer(seconds, self._auto_stop)
            self._timer.daemon = True
            self._timer.start()
            started = dict(self._active)
        from yuma_simulation_tpu.utils.logging import log_event

        log_event(
            logger,
            "profile_started",
            mode=started["mode"],
            seconds=started["seconds"],
            artifact=started["artifact"],
        )
        return started

    def _auto_stop(self) -> None:
        try:
            self.stop()
        except Exception:  # noqa: BLE001 — the timer thread must die quiet
            logger.warning("profile auto-stop failed", exc_info=True)

    def stop(self) -> Optional[dict]:
        """Close the active trace window (idempotent: returns ``None``
        when no window is open — the timer and an operator stop racing
        is the normal case, not an error)."""
        with self._lock:
            active = self._active
            self._active = None
            timer, self._timer = self._timer, None
        if active is None:
            return None
        if timer is not None:
            timer.cancel()
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — a torn trace still gets a record
            logger.warning("jax.profiler.stop_trace failed", exc_info=True)
        return self._publish(active)

    def _publish(self, rec: dict) -> dict:
        record = {
            "event": "profile_published",
            "mode": rec["mode"],
            "serial": rec["serial"],
            "artifact": rec["artifact"],
            "seconds": rec["seconds"],
        }
        try:
            from yuma_simulation_tpu.telemetry.flight import FlightRecorder

            FlightRecorder(self.bundle_dir).record_profile(record)
        except Exception:  # noqa: BLE001 — publication must not kill stop()
            logger.warning("profile registration failed", exc_info=True)
        self._published += 1
        from yuma_simulation_tpu.utils.logging import log_event

        log_event(
            logger,
            "profile_published",
            mode=record["mode"],
            artifact=record["artifact"],
        )
        return record

    def close(self) -> None:
        """Host shutdown: stop any window so the trace is published
        rather than torn."""
        self.stop()


# -- the ops plane -----------------------------------------------------


class OpsPlane:
    """Transport-free debug surface shared by every standing host.

    The HTTP layer (serve tier), the replay controller and the fleet
    host each construct one of these with whatever substrate they
    actually have — every argument beyond ``bundle_dir`` is optional,
    and missing pieces simply leave their section out of
    :meth:`debug_vars` rather than failing the whole snapshot."""

    def __init__(
        self,
        bundle_dir: Optional[Union[str, pathlib.Path]] = None,
        *,
        registry=None,
        slo_engine=None,
        run=None,
    ):
        self.bundle_dir = (
            pathlib.Path(bundle_dir) if bundle_dir is not None else None
        )
        self.registry = registry
        self.slo_engine = slo_engine
        self.run = run
        self.profile = ProfileSession(self.bundle_dir)

    # -- /debug/vars ---------------------------------------------------

    def _segments_summary(self) -> dict:
        from yuma_simulation_tpu.telemetry import flight

        if self.bundle_dir is None:
            return {"rotation": False}
        root = self.bundle_dir / flight.SEGMENTS_DIR
        if not root.is_dir():
            return {"rotation": False}
        rec = flight.FlightRecorder(self.bundle_dir)
        segs = rec._segment_dirs()
        sealed = [s for s in segs if rec._segment_sealed(s)]
        out = {
            "rotation": True,
            "segments_total": len(segs),
            "segments_sealed": len(sealed),
            "bytes_retained": sum(
                rec._segment_bytes(s) for s in sealed
            ),
            "open_runs": rec.open_run_ids(),
        }
        tomb = self.bundle_dir / flight.COMPACTED_NAME
        if tomb.exists():
            try:
                import json

                out["compacted"] = json.loads(tomb.read_text())
            except (OSError, ValueError):
                pass
        return out

    def debug_vars(self) -> dict:
        """One non-blocking snapshot of the live process. Every section
        is independently contained: a wedged subsystem hides its own
        section instead of taking the endpoint down."""
        out: dict = {"t": round(time.time(), 6)}
        if self.registry is not None:
            try:
                out["metrics"] = self.registry.snapshot()
            except Exception:  # noqa: BLE001
                logger.warning("debug_vars metrics failed", exc_info=True)
        if self.slo_engine is not None:
            try:
                out["slo"] = self.slo_engine.evaluate()
            except Exception:  # noqa: BLE001
                logger.warning("debug_vars slo failed", exc_info=True)
        try:
            from yuma_simulation_tpu.telemetry.slo import dispatch_snapshot

            sketches = dispatch_snapshot()
            if sketches:
                out["dispatch_sketches"] = sketches
        except Exception:  # noqa: BLE001
            logger.warning("debug_vars sketches failed", exc_info=True)
        out["events"] = recent_events()
        out["profile"] = self.profile.status()
        try:
            out["segments"] = self._segments_summary()
        except Exception:  # noqa: BLE001
            logger.warning("debug_vars segments failed", exc_info=True)
        return out

    # -- /debug/spans --------------------------------------------------

    def debug_spans(self, run_id: Optional[str] = None) -> dict:
        """One run's span tree, stitched from the sealed bundle plus
        the live (unflushed) run context. Defaults to the host's own
        run when no ``run_id`` is given."""
        from yuma_simulation_tpu.telemetry.flight import (
            build_timeline,
            load_bundle,
        )

        if not run_id and self.run is not None:
            run_id = self.run.run_id
        if not run_id:
            raise ValueError("no run_id given and the host has no run")
        if self.bundle_dir is None:
            raise ValueError(
                "span inspection requires a bundle directory"
            )
        bundle = load_bundle(self.bundle_dir)
        if self.run is not None and self.run.run_id == run_id:
            # Stitch in live (unflushed) spans: the bundle's copy of a
            # span wins (it is the sealed truth), the live ring only
            # fills in what the recorder hasn't published yet.
            seen = {
                (s.get("run_id"), s.get("span_id")) for s in bundle.spans
            }
            for s in self.run.span_records():
                if (s.get("run_id"), s.get("span_id")) not in seen:
                    bundle.spans.append(s)
        return build_timeline(bundle, run_id)

    # -- /debug/incidents ----------------------------------------------

    def debug_incidents(self) -> dict:
        """Current incident state from the bundle's durable
        ``incidents.jsonl`` (last record per incident id). A host with
        no bundle — or a clean one that never opened an incident —
        reports an empty list, which is the control-arm contract."""
        from yuma_simulation_tpu.telemetry.incident import load_incidents

        incidents = (
            load_incidents(self.bundle_dir)
            if self.bundle_dir is not None
            else []
        )
        return {
            "incidents": incidents,
            "open": sum(
                1 for r in incidents if r.get("state") == "open"
            ),
        }

    # -- /debug/profile ------------------------------------------------

    def debug_profile(self, seconds: float, mode: str = "trace") -> dict:
        """Kick one guarded profile window; see :class:`ProfileSession`."""
        return self.profile.start(seconds, mode=mode)

    def close(self) -> None:
        self.profile.close()
