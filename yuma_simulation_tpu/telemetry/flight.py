"""The sweep flight recorder: one on-disk bundle per supervised run.

A supervised sweep already leaves a crash-safe ledger
(:class:`..resilience.supervisor.FailureLedger`); this module adds the
two sides the ledger cannot tell on its own — WHEN everything happened
(the span tree) and HOW FAST/BIG it was (metrics snapshots) — and the
loader/consistency half that `tools/obsreport.py` renders.

Bundle layout (inside the supervisor's checkpoint `directory`):

- ``ledger.jsonl``  — per-unit outcomes (the supervisor writes it live,
  each record stamped with ``run_id``/``span_id``/``t``);
- ``spans.jsonl``   — every closed span of every run, close order
  (appended per run, atomic whole-file republish);
- ``metrics.jsonl`` — one registry snapshot line per run;
- ``costs.jsonl``   — AOT cost records (:class:`..cost.CostRecord`
  lines, run-stamped) when anything captured them — the supervisor's
  opt-in, bench, or an operator's explicit capture;
- ``numerics.jsonl`` — per-epoch tensor-stat records
  (:mod:`..numerics`): one line per (unit, stream, role) with per-lane
  finite fraction / min / max / absmax and the bit-cast-u32 reduction
  fingerprint, primary and canary roles side by side — what
  ``tools/driftreport.py --check`` compares;
- ``report.json``   — the LAST run's :class:`SweepHealthReport` (plus
  its ``run_id``), for the ledger<->report cross-check.

All four accumulate across resumes — the bundle is the full history of
the directory, grouped by ``run_id``. Every sink publishes atomically
(temp + fsync + rename) and every loader tolerates torn/undecodable
lines, matching the ledger's crash-safety contract; the formats are
ADDITIVE over PR 3's (old readers still parse — new keys only).

Continuous mode (0.23.0): a *standing* service (the replay controller,
the serve tier) never closes, so the monolithic whole-file republish
above is O(total-spans) per flush and the bundle grows without bound.
Rotation (:class:`RotationPolicy`, opt-in via the ``rotation=``
argument or ``YUMA_TPU_FLIGHT_ROTATE=1``; default OFF) re-routes the
span/metrics/numerics streams into crash-safe segment files::

    segments/seg_000000/{open.json, spans.jsonl, metrics.jsonl,
                         numerics.jsonl, seal.json}

The live segment is append-only (``append_durable`` — O(batch) on the
hot thread, torn-tail-tolerant like the watermark store); when it
exceeds the policy's size/age bound it is SEALED by publishing
``seal.json`` atomically (a ``segment_sealed`` record naming the
segment's run ids and byte size), and the next append opens the next
segment. Retention compaction deletes the oldest sealed segments past
``max_retained_bytes`` — never one whose run ids intersect the open
runs registered via :meth:`FlightRecorder.mark_run_open` — and leaves
an atomic ``compacted.json`` tombstone so ``check_bundle`` can exempt
exactly the history that was traded for bounded disk. ``ledger.jsonl``
/ ``report.json`` / ``slo.json`` / ``costs.jsonl`` stay at the root
(already O(batch) or point-in-time singletons). ``profiles.jsonl``
(root, append-only) registers on-demand profiler trace artifacts.
:func:`load_bundle` unions root + segments (newest span per
``(run_id, span_id)`` wins, numerics deduped by identity) so
``check_bundle``/``merge_bundles``/``check_stitched`` and every gate
read segmented and monolithic bundles identically — a monolithic
bundle (no ``segments/``) loads bit-for-bit as before.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import pathlib
import shutil
import time
from typing import Optional, Union

from yuma_simulation_tpu.telemetry.metrics import (
    MetricsRegistry,
    get_registry,
)
from yuma_simulation_tpu.telemetry.runctx import RunContext
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)

LEDGER_NAME = "ledger.jsonl"
SPANS_NAME = "spans.jsonl"
METRICS_NAME = "metrics.jsonl"
COSTS_NAME = "costs.jsonl"
REPORT_NAME = "report.json"
SLO_NAME = "slo.json"
NUMERICS_NAME = "numerics.jsonl"
SEGMENTS_DIR = "segments"
SEGMENT_PREFIX = "seg_"
SEAL_NAME = "seal.json"
OPEN_NAME = "open.json"
COMPACTED_NAME = "compacted.json"
OPEN_RUNS_NAME = "open_runs.json"
PROFILES_NAME = "profiles.jsonl"
INCIDENTS_NAME = "incidents.jsonl"

#: Env opt-in for rotation (see :class:`RotationPolicy`): "1"/"true"
#: turns it on with defaults for processes whose construction the
#: operator does not control (the supervisor inside a CLI sweep).
ROTATE_ENV = "YUMA_TPU_FLIGHT_ROTATE"


@dataclasses.dataclass(frozen=True)
class RotationPolicy:
    """When and how the segmented flight recorder rotates.

    A segment seals when its JSONL payload exceeds
    ``max_segment_bytes`` OR its age exceeds
    ``max_segment_age_seconds`` (either bound <= 0 disables that
    trigger). Retention keeps every sealed segment until their total
    size exceeds ``max_retained_bytes`` (<= 0 = keep everything), then
    deletes oldest-first — but never below ``min_retained_segments``
    sealed segments, and NEVER a segment whose recorded run ids
    intersect the directory's open runs (:meth:`FlightRecorder
    .mark_run_open`)."""

    max_segment_bytes: int = 1 << 20
    max_segment_age_seconds: float = 300.0
    max_retained_bytes: int = 0
    min_retained_segments: int = 2


def rotation_from_env() -> Optional[RotationPolicy]:
    """The :data:`ROTATE_ENV` opt-in: a default policy when set truthy,
    else None (rotation stays off — the 0.22-and-earlier behavior).
    An integer value > 1 is a segment byte bound (``"1"`` stays the
    plain on-with-defaults spelling): the CI soak lane uses a small
    bound so rotation demonstrably seals within a short run."""
    raw = os.environ.get(ROTATE_ENV, "").strip().lower()
    if raw in ("", "0", "false", "no", "off"):
        return None
    if raw.isdigit() and int(raw) > 1:
        return RotationPolicy(max_segment_bytes=int(raw))
    return RotationPolicy()

#: The SweepHealthReport action counts the ledger must reproduce exactly
#: (report field -> derivation, see :func:`ledger_counts`).
CROSS_CHECKED_COUNTS = (
    "stalls_killed",
    "units_requeued",
    "engine_demotions",
    "mesh_shrinks",
    "lanes_quarantined",
    # 0.14.0 — numerics-canary accounting (additive: pre-0.14 reports
    # lack the keys and are skipped by the `key in fields` guard).
    "canaries_run",
    "drift_events",
)


def _read_jsonl(path: pathlib.Path) -> list[dict]:
    """The shared tolerant JSONL reader (see
    :func:`..utils.checkpoint.read_jsonl_tolerant`) — lazy import to
    keep this module import-light."""
    from yuma_simulation_tpu.utils.checkpoint import read_jsonl_tolerant

    return read_jsonl_tolerant(path)


class FlightRecorder:
    """Writes the per-run bundle. One instance per directory; `record`
    is called once per run by the supervisor (success AND failure paths
    — a crashed sweep's spans are exactly the ones worth keeping).

    `rotation` (a :class:`RotationPolicy`; default: the
    :data:`ROTATE_ENV` opt-in, else None/off) switches the span/
    metrics/numerics streams into segmented continuous mode — see the
    module docstring. The recorder itself is stateless across
    instances: segment liveness, open-run registration, and tombstones
    all live on disk, so a fresh ``FlightRecorder(dir)`` per flush (the
    serving tier's pattern) continues exactly where the last left off."""

    def __init__(
        self,
        directory: Union[str, pathlib.Path],
        *,
        rotation: Optional[RotationPolicy] = None,
    ):
        self.directory = pathlib.Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.rotation = (
            rotation if rotation is not None else rotation_from_env()
        )

    # -- segmented continuous mode --------------------------------------

    def _segments_root(self) -> pathlib.Path:
        return self.directory / SEGMENTS_DIR

    def _segment_dirs(self) -> list[pathlib.Path]:
        root = self._segments_root()
        if not root.is_dir():
            return []
        out = []
        for p in root.iterdir():
            tail = p.name[len(SEGMENT_PREFIX):]
            if p.is_dir() and p.name.startswith(SEGMENT_PREFIX) and tail.isdigit():
                out.append(p)
        return sorted(out, key=lambda p: int(p.name[len(SEGMENT_PREFIX):]))

    @staticmethod
    def _segment_sealed(seg: pathlib.Path) -> bool:
        return (seg / SEAL_NAME).exists()

    @staticmethod
    def _segment_bytes(seg: pathlib.Path) -> int:
        total = 0
        for name in (SPANS_NAME, METRICS_NAME, NUMERICS_NAME):
            try:
                total += (seg / name).stat().st_size
            except OSError:
                continue
        return total

    def _open_segment(self, index: int) -> pathlib.Path:
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        seg = self._segments_root() / f"{SEGMENT_PREFIX}{index:06d}"
        seg.mkdir(parents=True, exist_ok=True)
        if not (seg / OPEN_NAME).exists():
            publish_atomic(
                seg / OPEN_NAME,
                json.dumps(
                    {"index": index, "t_opened": round(time.time(), 6)}
                ).encode(),
            )
        return seg

    def live_segment(self) -> pathlib.Path:
        """The segment the next append lands in: the highest-numbered
        unsealed one (a restarted writer continues its predecessor's
        open segment — at most its torn tail is at risk), else a fresh
        segment after the highest sealed index."""
        segs = self._segment_dirs()
        if segs and not self._segment_sealed(segs[-1]):
            return segs[-1]
        nxt = (
            int(segs[-1].name[len(SEGMENT_PREFIX):]) + 1 if segs else 0
        )
        return self._open_segment(nxt)

    def mark_run_open(self, run_id: str) -> None:
        """Register `run_id` as OPEN in this directory: retention will
        never delete a sealed segment holding its records. Long-lived
        hosts register their lifetime run at startup; idempotent."""
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        runs = set(self.open_run_ids())
        if run_id in runs:
            return
        runs.add(run_id)
        publish_atomic(
            self.directory / OPEN_RUNS_NAME,
            json.dumps({"run_ids": sorted(runs)}).encode(),
        )

    def mark_run_closed(self, run_id: str) -> None:
        """Release `run_id`'s retention pin (idempotent)."""
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        runs = set(self.open_run_ids())
        if run_id not in runs:
            return
        runs.discard(run_id)
        publish_atomic(
            self.directory / OPEN_RUNS_NAME,
            json.dumps({"run_ids": sorted(runs)}).encode(),
        )

    def open_run_ids(self) -> list[str]:
        path = self.directory / OPEN_RUNS_NAME
        if not path.exists():
            return []
        try:
            return [
                str(r) for r in json.loads(path.read_text()).get("run_ids", [])
            ]
        except (json.JSONDecodeError, OSError):
            return []

    def seal_live_segment(self) -> Optional[pathlib.Path]:
        """Seal the live segment NOW (rotation normally does this when a
        bound trips): publish its ``seal.json`` atomically, bump the
        telemetry metrics, run retention. Returns the sealed segment
        (None when the live segment holds no records yet — an empty
        seal would be noise)."""
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        segs = self._segment_dirs()
        if not segs or self._segment_sealed(segs[-1]):
            return None  # nothing live — and never mint an empty one
        seg = segs[-1]
        size = self._segment_bytes(seg)
        if size == 0:
            return None
        run_ids: dict[str, None] = {}
        for name in (SPANS_NAME, NUMERICS_NAME, METRICS_NAME):
            for rec in _read_jsonl(seg / name):
                rid = rec.get("run_id")
                if rid:
                    run_ids.setdefault(str(rid), None)
        index = int(seg.name[len(SEGMENT_PREFIX):])
        seal = {
            "event": "segment_sealed",
            "segment": seg.name,
            "index": index,
            "t": round(time.time(), 6),
            "bytes": size,
            "run_ids": list(run_ids),
        }
        publish_atomic(seg / SEAL_NAME, json.dumps(seal, sort_keys=True).encode())
        log_event(
            logger,
            "segment_sealed",
            segment=seg.name,
            t=seal["t"],
            bytes=size,
            run_ids=",".join(run_ids),
            runs=len(run_ids),
        )
        reg = get_registry()
        reg.counter(
            "telemetry_segments_total",
            help="flight-recorder segments sealed by rotation",
        ).inc()
        self._compact_retained()
        reg.gauge(
            "telemetry_bytes_retained",
            help="bytes of sealed flight segments currently retained",
        ).set(
            sum(
                self._segment_bytes(s)
                for s in self._segment_dirs()
                if self._segment_sealed(s)
            )
        )
        return seg

    def _maybe_rotate(self) -> None:
        """Post-append trigger: seal the live segment once a size/age
        bound trips. Contained — rotation must never fail the flush
        that fed it."""
        policy = self.rotation
        if policy is None:
            return
        try:
            seg = self.live_segment()
            size = self._segment_bytes(seg)
            if size == 0:
                return
            over_size = (
                policy.max_segment_bytes > 0
                and size >= policy.max_segment_bytes
            )
            over_age = False
            if policy.max_segment_age_seconds > 0:
                try:
                    opened = float(
                        json.loads((seg / OPEN_NAME).read_text()).get(
                            "t_opened", 0.0
                        )
                    )
                except (OSError, json.JSONDecodeError, ValueError):
                    opened = 0.0
                over_age = (
                    opened > 0
                    and time.time() - opened
                    >= policy.max_segment_age_seconds
                )
            if over_size or over_age:
                self.seal_live_segment()
        except Exception:
            logger.warning(
                "segment rotation failed in %s", self.directory,
                exc_info=True,
            )

    def _compact_retained(self) -> None:
        """Retention: delete oldest sealed segments past the policy's
        ``max_retained_bytes``, skipping any whose run ids intersect
        the open runs; each pass merges into the atomic
        ``compacted.json`` tombstone that check_bundle honors."""
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        policy = self.rotation
        if policy is None or policy.max_retained_bytes <= 0:
            return
        open_runs = set(self.open_run_ids())
        sealed = [s for s in self._segment_dirs() if self._segment_sealed(s)]
        sizes = {s: self._segment_bytes(s) for s in sealed}
        total = sum(sizes.values())
        dropped: list[dict] = []
        for seg in sealed:
            if (
                total <= policy.max_retained_bytes
                or len(sealed) - len(dropped)
                <= max(0, policy.min_retained_segments)
            ):
                break
            try:
                seal = json.loads((seg / SEAL_NAME).read_text())
            except (OSError, json.JSONDecodeError):
                seal = {"segment": seg.name, "run_ids": []}
            if open_runs & set(seal.get("run_ids", ())):
                # An open run's history is live evidence: a segment it
                # touched is never reclaimed, whatever the byte bound
                # says. (Oldest-first means later segments may still
                # free space below.)
                continue
            shutil.rmtree(seg, ignore_errors=True)
            total -= sizes[seg]
            dropped.append(
                {
                    "segment": seal.get("segment", seg.name),
                    "bytes": sizes[seg],
                    "run_ids": list(seal.get("run_ids", ())),
                }
            )
        if not dropped:
            return
        path = self.directory / COMPACTED_NAME
        prior = {"segments": 0, "bytes": 0, "run_ids": []}
        if path.exists():
            try:
                prior.update(json.loads(path.read_text()))
            except (OSError, json.JSONDecodeError):
                pass
        run_ids = set(prior.get("run_ids", ())) | {
            rid for d in dropped for rid in d["run_ids"]
        }
        tombstone = {
            "event": "segments_compacted",
            "t": round(time.time(), 6),
            "segments": int(prior.get("segments", 0)) + len(dropped),
            "bytes": int(prior.get("bytes", 0))
            + sum(d["bytes"] for d in dropped),
            "run_ids": sorted(run_ids),
        }
        publish_atomic(path, json.dumps(tombstone, sort_keys=True).encode())
        log_event(
            logger,
            "segments_compacted",
            segments=len(dropped),
            bytes=sum(d["bytes"] for d in dropped),
        )

    def record_profile(self, record: dict) -> None:
        """Register one on-demand profiler capture (`profile_published`
        consumers read ``profiles.jsonl``): append-only at the bundle
        root — profile sessions are rare and their artifact directories
        live outside the rotation streams."""
        from yuma_simulation_tpu.utils.checkpoint import append_durable

        line = dict(record)
        line.setdefault("t", round(time.time(), 6))
        append_durable(
            self.directory / PROFILES_NAME,
            (json.dumps(line, sort_keys=True) + "\n").encode(),
        )

    def record_incident(self, record: dict) -> None:
        """Append one incident state record (``incidents.jsonl``): an
        append-only root sink like ``profiles.jsonl`` — incidents are
        rare, span segment rotations, and re-append their full state on
        every transition, so readers keep the LAST record per incident
        id and a torn tail costs one transition, never history."""
        from yuma_simulation_tpu.utils.checkpoint import append_durable

        line = dict(record)
        line.setdefault("t", round(time.time(), 6))
        append_durable(
            self.directory / INCIDENTS_NAME,
            (json.dumps(line, sort_keys=True) + "\n").encode(),
        )

    def record(
        self,
        run: RunContext,
        *,
        registry: Optional[MetricsRegistry] = None,
        report=None,
        extra_runs=(),
        slo_engine=None,
    ) -> None:
        """Append `run`'s spans to ``spans.jsonl``, one registry
        snapshot line to ``metrics.jsonl``, and (when given) publish the
        run's health report to ``report.json``.

        Spans are merged by ``(run_id, span_id)``, newest wins: a
        mid-run publish records still-open ancestors as
        ``status="open"``, and a later publish of the same run (a second
        supervised sweep under one operator RunContext) replaces them
        with their closed form instead of duplicating them.
        `extra_runs` (further :class:`RunContext`s — e.g. a server's
        per-request ingress runs continuing remote traces) merge into
        the SAME republish so a bundle publish stays one atomic write
        per sink.

        The process SLO state (:mod:`..slo`) publishes alongside as
        ``slo.json`` whenever an engine with specs exists — pass
        `slo_engine` to pin a specific one (the serving tier's), default
        is the process engine. SLO capture failures are contained: the
        span/metrics record above must never be misreported as failed
        because the SLO snapshot was."""
        from yuma_simulation_tpu.utils.checkpoint import (
            append_durable,
            publish_atomic,
        )

        new_records: list = run.span_records()
        for extra in extra_runs:
            new_records.extend(extra.span_records())
        reg = registry if registry is not None else get_registry()
        if self.rotation is not None:
            # Continuous mode: O(batch) appends into the live segment —
            # the loader's (run_id, span_id) newest-wins dedupe supplies
            # the open->closed span replacement the monolithic merge
            # used to do, and rotation bounds what any one file holds.
            if new_records:
                append_durable(
                    self.live_segment() / SPANS_NAME,
                    "".join(
                        json.dumps(s, sort_keys=True) + "\n"
                        for s in new_records
                    ).encode(),
                )
        else:
            spans_path = self.directory / SPANS_NAME
            merged: dict[tuple, dict] = {}
            for rec in _read_jsonl(spans_path) + new_records:
                merged[(rec.get("run_id"), rec.get("span_id"))] = rec
            payload = "".join(
                json.dumps(s, sort_keys=True) + "\n" for s in merged.values()
            )
            publish_atomic(spans_path, payload.encode())
        self.snapshot_metrics(reg, run_id=run.run_id)

        if report is not None:
            publish_atomic(
                self.directory / REPORT_NAME,
                json.dumps(
                    {
                        "run_id": run.run_id,
                        "report": dataclasses.asdict(report),
                    },
                    sort_keys=True,
                ).encode(),
            )
        try:
            self.record_slo(slo_engine, run_id=run.run_id)
        except Exception:
            logger.warning(
                "SLO snapshot publish failed for %s", self.directory,
                exc_info=True,
            )

    def snapshot_metrics(self, registry=None, **meta) -> None:
        """One metrics-registry snapshot line into the bundle, routed
        by mode: under rotation an O(1) durable append into the live
        segment (which may seal it), monolithic the atomic whole-file
        publish. The dispatch timing sketches
        (:func:`..slo.dispatch_snapshot`) ride along as plain meta
        (additive — old readers ignore unknown keys); perfattrib joins
        them against the bundle's cost records."""
        reg = registry if registry is not None else get_registry()
        try:
            from yuma_simulation_tpu.telemetry.slo import dispatch_snapshot

            sketches = dispatch_snapshot()
            if sketches:
                meta.setdefault("dispatch_sketches", sketches)
        except Exception:
            logger.warning("dispatch sketch capture failed", exc_info=True)
        if self.rotation is not None:
            reg.append_snapshot(self.live_segment() / METRICS_NAME, **meta)
            self._maybe_rotate()
        else:
            reg.publish_snapshot(self.directory / METRICS_NAME, **meta)

    def append_spans(self, runs) -> None:
        """Append completed runs' span records to ``spans.jsonl``
        WITHOUT the whole-file merge :meth:`record` does — O(batch),
        for a long-lived server's periodic ingress flushes (a full
        merge republish there is O(total-spans) on a request handler
        thread and quadratic over the server's lifetime). Callers must
        serialize against concurrent publishes to the same directory
        (the serving tier's publish lock) and flush each run at most
        once: nothing here dedupes — the next full :meth:`record`
        (close) merges by identity and republishes atomically, which
        also heals a torn tail from a crash mid-append (readers are
        torn-tail tolerant). Under rotation the append lands in the
        LIVE SEGMENT only — flush cost stays O(batch) however many
        sealed segments the directory has accumulated — and may seal
        it."""
        records: list = []
        for run in runs:
            records.extend(run.span_records())
        if not records:
            return
        payload = "".join(
            json.dumps(s, sort_keys=True) + "\n" for s in records
        )
        from yuma_simulation_tpu.utils.checkpoint import append_durable

        if self.rotation is not None:
            append_durable(self.live_segment() / SPANS_NAME, payload.encode())
            self._maybe_rotate()
        else:
            append_durable(self.directory / SPANS_NAME, payload.encode())

    def append_numerics(
        self, records, *, run_id: Optional[str] = None
    ) -> None:
        """Append numerics records to ``numerics.jsonl`` WITHOUT the
        whole-file merge :meth:`record_numerics` does — the
        :meth:`append_spans` contract applied to the numerics stream
        (O(batch) on a handler thread, caller serializes publishes,
        the next full :meth:`record_numerics` merge dedupes by
        identity and heals a torn tail)."""
        lines = []
        for rec in records:
            line = dict(rec)
            if run_id is not None:
                line["run_id"] = run_id
            lines.append(line)
        if not lines:
            return
        payload = "".join(
            json.dumps(r, sort_keys=True) + "\n" for r in lines
        )
        from yuma_simulation_tpu.utils.checkpoint import append_durable

        if self.rotation is not None:
            append_durable(
                self.live_segment() / NUMERICS_NAME, payload.encode()
            )
            self._maybe_rotate()
        else:
            append_durable(self.directory / NUMERICS_NAME, payload.encode())

    def record_slo(self, engine=None, *, run_id: Optional[str] = None) -> None:
        """Publish the SLO engine's state (specs, per-SLO burn state,
        sketches, alert history) as ``slo.json`` — what
        ``tools/sloreport.py`` renders and gates. No engine / no specs
        -> no file (a bundle without SLOs stays additive for old
        readers)."""
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        if engine is None:
            from yuma_simulation_tpu.telemetry.slo import peek_slo_engine

            engine = peek_slo_engine()
        if engine is None or not engine.specs:
            return
        snap = engine.snapshot()
        if run_id is not None:
            snap["run_id"] = run_id
        publish_atomic(
            self.directory / SLO_NAME,
            json.dumps(snap, sort_keys=True).encode(),
        )

    def record_numerics(
        self, records, *, run_id: Optional[str] = None
    ) -> None:
        """Append per-epoch numerics records (the serialized sketches
        of :func:`..numerics.sketch_records`) to ``numerics.jsonl``,
        each stamped with `run_id`. Merged by the engine-free
        :func:`..numerics.numerics_identity`, newest wins — so the
        stream SURVIVES a failed/resumed sweep exactly like
        ``costs.jsonl``: a resumed run's bundle keeps the prior run's
        records for units it never re-executed, and a re-executed
        unit's capture replaces its prior line instead of duplicating
        it — even when the retry landed on a DIFFERENT rung (a stale
        other-engine primary left behind would mispair against later
        canaries)."""
        from yuma_simulation_tpu.telemetry.numerics import (
            numerics_identity,
        )
        from yuma_simulation_tpu.utils.checkpoint import (
            append_durable,
            publish_atomic,
        )

        lines = []
        for rec in records:
            line = dict(rec)
            if run_id is not None:
                line["run_id"] = run_id
            lines.append(line)
        if self.rotation is not None:
            # Continuous mode: O(batch) — the loader's identity dedupe
            # (newest wins) replaces the monolithic merge below.
            if lines:
                append_durable(
                    self.live_segment() / NUMERICS_NAME,
                    "".join(
                        json.dumps(r, sort_keys=True) + "\n" for r in lines
                    ).encode(),
                )
                self._maybe_rotate()
            return
        if not lines and not (self.directory / NUMERICS_NAME).exists():
            return
        path = self.directory / NUMERICS_NAME
        merged: dict[tuple, dict] = {}
        for rec in _read_jsonl(path) + lines:
            merged[numerics_identity(rec)] = rec
        publish_atomic(
            path,
            "".join(
                json.dumps(r, sort_keys=True) + "\n" for r in merged.values()
            ).encode(),
        )

    def record_costs(self, records, *, run_id: Optional[str] = None) -> None:
        """Append AOT cost records (``CostRecord`` instances or their
        ``to_json`` dicts) to ``costs.jsonl``, each stamped with
        `run_id`. Merged by ``(run_id, engine, V, M, epochs)``, newest
        wins — a resumed run's re-capture replaces its prior line
        instead of duplicating it; distinct shapes/runs accumulate."""
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        lines = []
        for rec in (
            records.values() if isinstance(records, dict) else records
        ):
            line = rec.to_json() if hasattr(rec, "to_json") else dict(rec)
            if run_id is not None:
                line["run_id"] = run_id
            lines.append(line)
        path = self.directory / COSTS_NAME
        merged: dict[tuple, dict] = {}
        for rec in _read_jsonl(path) + lines:
            merged[
                (
                    rec.get("run_id"),
                    rec.get("engine"),
                    rec.get("V"),
                    rec.get("M"),
                    rec.get("epochs"),
                )
            ] = rec
        publish_atomic(
            path,
            "".join(
                json.dumps(r, sort_keys=True) + "\n" for r in merged.values()
            ).encode(),
        )


@dataclasses.dataclass
class Bundle:
    """A loaded flight-recorder bundle (see the module docstring)."""

    directory: pathlib.Path
    spans: list
    metrics: list
    ledger: list
    report: Optional[dict] = None
    costs: list = dataclasses.field(default_factory=list)
    slo: Optional[dict] = None
    numerics: list = dataclasses.field(default_factory=list)
    #: sealed-segment ``seal.json`` records, index order (continuous
    #: mode; empty for monolithic bundles).
    segments: list = dataclasses.field(default_factory=list)
    #: registered profiler captures (``profiles.jsonl``).
    profiles: list = dataclasses.field(default_factory=list)
    #: raw incident state records (``incidents.jsonl``), append order —
    #: every transition re-appends the incident's full state; dedupe to
    #: current state via :func:`..incident.latest_incidents`.
    incidents: list = dataclasses.field(default_factory=list)
    #: the retention tombstone (``compacted.json``) when compaction has
    #: reclaimed sealed segments, else None.
    compacted: Optional[dict] = None

    def run_ids(self) -> list[str]:
        """Distinct run ids, first-seen order (spans then ledger)."""
        seen: dict[str, None] = {}
        for rec in list(self.spans) + list(self.ledger):
            rid = rec.get("run_id")
            if rid:
                seen.setdefault(rid, None)
        return list(seen)

    def latest_run_id(self) -> Optional[str]:
        ids = self.run_ids()
        return ids[-1] if ids else None


def load_bundle(directory: Union[str, pathlib.Path]) -> Bundle:
    """Load a bundle, monolithic or segmented, as ONE logical Bundle.

    Root sinks load exactly as they always did; when a ``segments/``
    directory exists, every segment's streams are unioned in (segment
    index order, so chronology holds) and deduped — spans by
    ``(run_id, span_id)`` and numerics by identity, newest wins,
    reproducing the open->closed replacement the monolithic merge
    republish performed at write time. A bundle without ``segments/``
    takes none of these paths: monolithic bundles load bit-for-bit as
    before."""
    directory = pathlib.Path(directory)

    def _json_file(name: str) -> Optional[dict]:
        path = directory / name
        if not path.exists():
            return None
        try:
            return json.loads(path.read_text())
        except json.JSONDecodeError:
            logger.warning("undecodable %s in %s", name, directory)
            return None

    spans = _read_jsonl(directory / SPANS_NAME)
    metrics = _read_jsonl(directory / METRICS_NAME)
    numerics = _read_jsonl(directory / NUMERICS_NAME)
    segments: list = []
    seg_root = directory / SEGMENTS_DIR
    if seg_root.is_dir():
        seg_dirs = []
        for p in seg_root.iterdir():
            tail = p.name[len(SEGMENT_PREFIX):]
            if p.is_dir() and p.name.startswith(SEGMENT_PREFIX) and tail.isdigit():
                seg_dirs.append(p)
        seg_dirs.sort(key=lambda p: int(p.name[len(SEGMENT_PREFIX):]))
        for seg in seg_dirs:
            spans.extend(_read_jsonl(seg / SPANS_NAME))
            metrics.extend(_read_jsonl(seg / METRICS_NAME))
            numerics.extend(_read_jsonl(seg / NUMERICS_NAME))
            seal_path = seg / SEAL_NAME
            if seal_path.exists():
                try:
                    segments.append(json.loads(seal_path.read_text()))
                except (OSError, json.JSONDecodeError):
                    logger.warning("undecodable %s", seal_path)
        merged_spans: dict[tuple, dict] = {}
        for rec in spans:
            merged_spans[(rec.get("run_id"), rec.get("span_id"))] = rec
        spans = list(merged_spans.values())
        from yuma_simulation_tpu.telemetry.numerics import (
            numerics_identity,
        )

        merged_num: dict[tuple, dict] = {}
        for rec in numerics:
            merged_num[numerics_identity(rec)] = rec
        numerics = list(merged_num.values())

    return Bundle(
        directory=directory,
        spans=spans,
        metrics=metrics,
        ledger=_read_jsonl(directory / LEDGER_NAME),
        report=_json_file(REPORT_NAME),
        costs=_read_jsonl(directory / COSTS_NAME),
        slo=_json_file(SLO_NAME),
        numerics=numerics,
        segments=segments,
        profiles=_read_jsonl(directory / PROFILES_NAME),
        incidents=_read_jsonl(directory / INCIDENTS_NAME),
        compacted=_json_file(COMPACTED_NAME),
    )


def ledger_counts(ledger: list, run_id: str) -> dict:
    """The ledger-derived twin of the :class:`SweepHealthReport` action
    counts for one run. Quarantine provenance follows the supervisor's
    resume rule: the RETURNED output carries each unit's LAST `unit_ok`
    record across the whole ledger, resumed units included."""
    this_run = [r for r in ledger if r.get("run_id") == run_id]
    oks = [r for r in this_run if r.get("event") == "unit_ok"]
    last_ok: dict = {}
    for r in ledger:
        if r.get("event") == "unit_ok" and "unit" in r:
            last_ok[r["unit"]] = r
    return {
        "stalls_killed": sum(
            1 for r in this_run if r.get("event") == "unit_stalled"
        ),
        # DISTINCT units, matching SweepHealthReport.units_requeued: a
        # unit torn twice emits one unit_requeued record per re-entry
        # but counts once in the report.
        "units_requeued": len(
            {
                r.get("unit")
                for r in this_run
                if r.get("event") == "unit_requeued"
            }
        ),
        "engine_demotions": sum(int(r.get("demotions", 0)) for r in oks),
        "mesh_shrinks": sum(int(r.get("mesh_shrinks", 0)) for r in oks),
        "lanes_quarantined": sum(
            len(r.get("quarantined", ())) for r in last_ok.values()
        ),
        "canaries_run": sum(int(r.get("canaries", 0)) for r in oks),
        "drift_events": sum(int(r.get("drifts", 0)) for r in oks),
    }


def check_bundle(bundle: Bundle) -> list[str]:
    """Consistency problems in a bundle (empty list = sound):

    - every ledger record must carry ``run_id``/``span_id`` resolving to
      a recorded span of that run (the obsreport ``--check`` gate);
    - every span's ``parent_id`` must resolve within its run — EXCEPT
      spans flagged ``remote_parent`` (a continued cross-process trace,
      :mod:`..propagation`): their parent lives in a sibling process's
      bundle and is checked by :func:`check_stitched` instead;
    - when ``report.json`` is present, its action counts must match the
      ledger-derived counts exactly (:data:`CROSS_CHECKED_COUNTS`);
    - every ``costs.jsonl`` record must name its engine, and a null
      analysis field must carry a ``reason`` (the explicit-null
      contract of :class:`..cost.CostRecord`);
    - every ``numerics.jsonl`` record must name its stream/engine/role
      and carry a per-lane fingerprint whose epoch length matches its
      declared ``epochs`` (the driftreport comparison basis — a record
      that cannot be compared is rot, not data).
    """
    from yuma_simulation_tpu.telemetry.numerics import (
        check_numerics_records,
    )

    problems: list[str] = list(check_numerics_records(bundle.numerics))
    for i, rec in enumerate(bundle.costs):
        if not rec.get("engine"):
            problems.append(f"costs[{i}] names no engine")
            continue
        for field in ("flops", "bytes_accessed", "peak_bytes"):
            if field in rec and rec[field] is None and not rec.get("reason"):
                problems.append(
                    f"costs[{i}] engine={rec['engine']} has null {field} "
                    "with no reason"
                )
    # Retention compaction (continuous mode) deletes whole sealed
    # segments; the tombstone names exactly the runs whose history was
    # traded for bounded disk, and ONLY those runs are exempt from the
    # resolution gates below — everything else is still held to them.
    compacted_runs: set = set()
    if bundle.compacted is not None:
        compacted_runs = {str(r) for r in bundle.compacted.get("run_ids", ())}
    spans_by_run: dict[str, set] = {}
    for s in bundle.spans:
        spans_by_run.setdefault(s.get("run_id", ""), set()).add(
            s.get("span_id")
        )
    for s in bundle.spans:
        parent = s.get("parent_id", "")
        if s.get("remote_parent"):
            continue  # resolved across bundles by check_stitched
        if s.get("run_id") in compacted_runs:
            continue  # parent may have been compacted away
        if parent and parent not in spans_by_run.get(s.get("run_id", ""), ()):
            problems.append(
                f"span {s.get('span_id')} (run {s.get('run_id')}) has "
                f"unresolvable parent {parent!r}"
            )
    for i, rec in enumerate(bundle.ledger):
        event = rec.get("event", "?")
        rid, sid = rec.get("run_id"), rec.get("span_id")
        if not rid or not sid:
            problems.append(
                f"ledger[{i}] event={event} lacks run/span identity "
                f"(run_id={rid!r} span_id={sid!r})"
            )
            continue
        if rid in compacted_runs:
            continue  # its span may have been compacted away
        if sid not in spans_by_run.get(rid, ()):
            problems.append(
                f"ledger[{i}] event={event} span {sid} does not resolve "
                f"in run {rid}"
            )
    if bundle.report is not None:
        rid = bundle.report.get("run_id")
        fields = bundle.report.get("report", {})
        if rid is None:
            problems.append("report.json carries no run_id")
        else:
            derived = ledger_counts(bundle.ledger, rid)
            for key in CROSS_CHECKED_COUNTS:
                if key in fields and int(fields[key]) != int(derived[key]):
                    problems.append(
                        f"report.{key}={fields[key]} but the ledger "
                        f"derives {derived[key]} for run {rid}"
                    )
    return problems


def merge_bundles(bundles, directory=None) -> Bundle:
    """The UNION of several sibling bundles (one per process of a
    distributed run) as one logical bundle: spans/ledger/metrics/costs
    concatenated, deduped by identity, time-ordered — what the stitched
    cross-process timeline renders. `report`/`slo` keep the first
    non-None (the driver's, by caller convention)."""
    spans: dict[tuple, dict] = {}
    ledger: list = []
    metrics: list = []
    costs: list = []
    numerics: list = []
    segments: list = []
    profiles: list = []
    incidents: list = []
    report = None
    slo = None
    compacted = None
    for b in bundles:
        for s in b.spans:
            spans.setdefault((s.get("run_id"), s.get("span_id")), s)
        ledger.extend(b.ledger)
        metrics.extend(b.metrics)
        costs.extend(b.costs)
        numerics.extend(b.numerics)
        segments.extend(b.segments)
        profiles.extend(b.profiles)
        incidents.extend(b.incidents)
        if report is None:
            report = b.report
        if slo is None:
            slo = b.slo
        if b.compacted is not None:
            if compacted is None:
                compacted = dict(b.compacted)
            else:
                # Union of sibling tombstones: counts add, run ids merge
                # — check_bundle's exemption must cover every sibling's
                # reclaimed history.
                compacted = {
                    "event": "segments_compacted",
                    "t": max(
                        float(compacted.get("t") or 0.0),
                        float(b.compacted.get("t") or 0.0),
                    ),
                    "segments": int(compacted.get("segments", 0))
                    + int(b.compacted.get("segments", 0)),
                    "bytes": int(compacted.get("bytes", 0))
                    + int(b.compacted.get("bytes", 0)),
                    "run_ids": sorted(
                        set(compacted.get("run_ids", ()))
                        | set(b.compacted.get("run_ids", ()))
                    ),
                }
    ledger.sort(key=lambda r: float(r.get("t") or 0.0))
    return Bundle(
        directory=pathlib.Path(directory) if directory else pathlib.Path("."),
        spans=sorted(
            spans.values(), key=lambda s: float(s.get("t_start") or 0.0)
        ),
        metrics=metrics,
        ledger=ledger,
        report=report,
        costs=costs,
        slo=slo,
        numerics=numerics,
        segments=segments,
        profiles=profiles,
        incidents=incidents,
        compacted=compacted,
    )


def check_stitched(bundles) -> list[str]:
    """The cross-process half of the orphan-span gate: over the UNION of
    sibling bundles, every span flagged ``remote_parent`` must resolve
    to a recorded span of the same run in SOME bundle, and every parent
    chain must terminate at a true root (empty ``parent_id``) without a
    cycle. A span whose remote parent no sibling recorded is an orphan —
    a tampered, truncated, or mis-propagated trace — and fails the
    check. Empty list = one sound stitched trace."""
    bundles = list(bundles)
    by_run: dict[str, dict[str, dict]] = {}
    for b in bundles:
        for s in b.spans:
            rid, sid = s.get("run_id", ""), s.get("span_id")
            if sid:
                by_run.setdefault(rid, {})[sid] = s
    problems: list[str] = []
    for rid, spans in sorted(by_run.items()):
        for sid, s in sorted(spans.items()):
            parent = s.get("parent_id", "")
            if parent and parent not in spans:
                problems.append(
                    f"span {sid} (run {rid}) is an orphan: parent "
                    f"{parent!r} resolves in no sibling bundle"
                )
        # Chain termination: walk each span to a root, bounded by the
        # span count so a cycle cannot hang the gate.
        for sid in sorted(spans):
            cur, hops = sid, 0
            while cur and hops <= len(spans):
                parent = spans[cur].get("parent_id", "")
                if not parent or parent not in spans:
                    break
                cur = parent
                hops += 1
            if hops > len(spans):
                problems.append(
                    f"span {sid} (run {rid}) sits on a parent cycle"
                )
    return problems


def build_timeline(bundle: Bundle, run_id: str) -> dict:
    """One run's recovery timeline: the span tree (children in start
    order) with each span's ledger records attached.

    Returns ``{"run_id", "spans": {span_id: span}, "roots": [span_id],
    "children": {span_id: [span_id]}, "records": {span_id: [ledger
    record]}}`` — everything obsreport needs to render, nothing
    presentation-specific."""
    spans = {
        s["span_id"]: s
        for s in bundle.spans
        if s.get("run_id") == run_id and s.get("span_id")
    }
    children: dict[str, list] = {sid: [] for sid in spans}
    roots: list[str] = []
    for sid, s in spans.items():
        parent = s.get("parent_id", "")
        if parent and parent in spans:
            children[parent].append(sid)
        else:
            roots.append(sid)

    def start(sid: str) -> float:
        return float(spans[sid].get("t_start") or 0.0)

    for sid in children:
        children[sid].sort(key=start)
    roots.sort(key=start)
    records: dict[str, list] = {}
    for rec in bundle.ledger:
        if rec.get("run_id") != run_id:
            continue
        records.setdefault(rec.get("span_id", ""), []).append(rec)
    for recs in records.values():
        recs.sort(key=lambda r: float(r.get("t") or 0.0))
    return {
        "run_id": run_id,
        "spans": spans,
        "roots": roots,
        "children": children,
        "records": records,
    }
