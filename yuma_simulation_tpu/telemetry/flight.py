"""The sweep flight recorder: one on-disk bundle per supervised run.

A supervised sweep already leaves a crash-safe ledger
(:class:`..resilience.supervisor.FailureLedger`); this module adds the
two sides the ledger cannot tell on its own — WHEN everything happened
(the span tree) and HOW FAST/BIG it was (metrics snapshots) — and the
loader/consistency half that `tools/obsreport.py` renders.

Bundle layout (inside the supervisor's checkpoint `directory`):

- ``ledger.jsonl``  — per-unit outcomes (the supervisor writes it live,
  each record stamped with ``run_id``/``span_id``/``t``);
- ``spans.jsonl``   — every closed span of every run, close order
  (appended per run, atomic whole-file republish);
- ``metrics.jsonl`` — one registry snapshot line per run;
- ``costs.jsonl``   — AOT cost records (:class:`..cost.CostRecord`
  lines, run-stamped) when anything captured them — the supervisor's
  opt-in, bench, or an operator's explicit capture;
- ``numerics.jsonl`` — per-epoch tensor-stat records
  (:mod:`..numerics`): one line per (unit, stream, role) with per-lane
  finite fraction / min / max / absmax and the bit-cast-u32 reduction
  fingerprint, primary and canary roles side by side — what
  ``tools/driftreport.py --check`` compares;
- ``report.json``   — the LAST run's :class:`SweepHealthReport` (plus
  its ``run_id``), for the ledger<->report cross-check.

All four accumulate across resumes — the bundle is the full history of
the directory, grouped by ``run_id``. Every sink publishes atomically
(temp + fsync + rename) and every loader tolerates torn/undecodable
lines, matching the ledger's crash-safety contract; the formats are
ADDITIVE over PR 3's (old readers still parse — new keys only).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import pathlib
from typing import Optional, Union

from yuma_simulation_tpu.telemetry.metrics import (
    MetricsRegistry,
    get_registry,
)
from yuma_simulation_tpu.telemetry.runctx import RunContext

logger = logging.getLogger(__name__)

LEDGER_NAME = "ledger.jsonl"
SPANS_NAME = "spans.jsonl"
METRICS_NAME = "metrics.jsonl"
COSTS_NAME = "costs.jsonl"
REPORT_NAME = "report.json"
SLO_NAME = "slo.json"
NUMERICS_NAME = "numerics.jsonl"

#: The SweepHealthReport action counts the ledger must reproduce exactly
#: (report field -> derivation, see :func:`ledger_counts`).
CROSS_CHECKED_COUNTS = (
    "stalls_killed",
    "units_requeued",
    "engine_demotions",
    "mesh_shrinks",
    "lanes_quarantined",
    # 0.14.0 — numerics-canary accounting (additive: pre-0.14 reports
    # lack the keys and are skipped by the `key in fields` guard).
    "canaries_run",
    "drift_events",
)


def _read_jsonl(path: pathlib.Path) -> list[dict]:
    """The shared tolerant JSONL reader (see
    :func:`..utils.checkpoint.read_jsonl_tolerant`) — lazy import to
    keep this module import-light."""
    from yuma_simulation_tpu.utils.checkpoint import read_jsonl_tolerant

    return read_jsonl_tolerant(path)


class FlightRecorder:
    """Writes the per-run bundle. One instance per directory; `record`
    is called once per run by the supervisor (success AND failure paths
    — a crashed sweep's spans are exactly the ones worth keeping)."""

    def __init__(self, directory: Union[str, pathlib.Path]):
        self.directory = pathlib.Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)

    def record(
        self,
        run: RunContext,
        *,
        registry: Optional[MetricsRegistry] = None,
        report=None,
        extra_runs=(),
        slo_engine=None,
    ) -> None:
        """Append `run`'s spans to ``spans.jsonl``, one registry
        snapshot line to ``metrics.jsonl``, and (when given) publish the
        run's health report to ``report.json``.

        Spans are merged by ``(run_id, span_id)``, newest wins: a
        mid-run publish records still-open ancestors as
        ``status="open"``, and a later publish of the same run (a second
        supervised sweep under one operator RunContext) replaces them
        with their closed form instead of duplicating them.
        `extra_runs` (further :class:`RunContext`s — e.g. a server's
        per-request ingress runs continuing remote traces) merge into
        the SAME republish so a bundle publish stays one atomic write
        per sink.

        The process SLO state (:mod:`..slo`) publishes alongside as
        ``slo.json`` whenever an engine with specs exists — pass
        `slo_engine` to pin a specific one (the serving tier's), default
        is the process engine. SLO capture failures are contained: the
        span/metrics record above must never be misreported as failed
        because the SLO snapshot was."""
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        spans_path = self.directory / SPANS_NAME
        merged: dict[tuple, dict] = {}
        new_records: list = run.span_records()
        for extra in extra_runs:
            new_records.extend(extra.span_records())
        for rec in _read_jsonl(spans_path) + new_records:
            merged[(rec.get("run_id"), rec.get("span_id"))] = rec
        payload = "".join(
            json.dumps(s, sort_keys=True) + "\n" for s in merged.values()
        )
        publish_atomic(spans_path, payload.encode())

        reg = registry if registry is not None else get_registry()
        reg.publish_snapshot(
            self.directory / METRICS_NAME, run_id=run.run_id
        )

        if report is not None:
            publish_atomic(
                self.directory / REPORT_NAME,
                json.dumps(
                    {
                        "run_id": run.run_id,
                        "report": dataclasses.asdict(report),
                    },
                    sort_keys=True,
                ).encode(),
            )
        try:
            self.record_slo(slo_engine, run_id=run.run_id)
        except Exception:
            logger.warning(
                "SLO snapshot publish failed for %s", self.directory,
                exc_info=True,
            )

    def append_spans(self, runs) -> None:
        """Append completed runs' span records to ``spans.jsonl``
        WITHOUT the whole-file merge :meth:`record` does — O(batch),
        for a long-lived server's periodic ingress flushes (a full
        merge republish there is O(total-spans) on a request handler
        thread and quadratic over the server's lifetime). Callers must
        serialize against concurrent publishes to the same directory
        (the serving tier's publish lock) and flush each run at most
        once: nothing here dedupes — the next full :meth:`record`
        (close) merges by identity and republishes atomically, which
        also heals a torn tail from a crash mid-append (readers are
        torn-tail tolerant)."""
        records: list = []
        for run in runs:
            records.extend(run.span_records())
        if not records:
            return
        payload = "".join(
            json.dumps(s, sort_keys=True) + "\n" for s in records
        )
        from yuma_simulation_tpu.utils.checkpoint import append_durable

        append_durable(self.directory / SPANS_NAME, payload.encode())

    def append_numerics(
        self, records, *, run_id: Optional[str] = None
    ) -> None:
        """Append numerics records to ``numerics.jsonl`` WITHOUT the
        whole-file merge :meth:`record_numerics` does — the
        :meth:`append_spans` contract applied to the numerics stream
        (O(batch) on a handler thread, caller serializes publishes,
        the next full :meth:`record_numerics` merge dedupes by
        identity and heals a torn tail)."""
        lines = []
        for rec in records:
            line = dict(rec)
            if run_id is not None:
                line["run_id"] = run_id
            lines.append(line)
        if not lines:
            return
        payload = "".join(
            json.dumps(r, sort_keys=True) + "\n" for r in lines
        )
        from yuma_simulation_tpu.utils.checkpoint import append_durable

        append_durable(self.directory / NUMERICS_NAME, payload.encode())

    def record_slo(self, engine=None, *, run_id: Optional[str] = None) -> None:
        """Publish the SLO engine's state (specs, per-SLO burn state,
        sketches, alert history) as ``slo.json`` — what
        ``tools/sloreport.py`` renders and gates. No engine / no specs
        -> no file (a bundle without SLOs stays additive for old
        readers)."""
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        if engine is None:
            from yuma_simulation_tpu.telemetry.slo import peek_slo_engine

            engine = peek_slo_engine()
        if engine is None or not engine.specs:
            return
        snap = engine.snapshot()
        if run_id is not None:
            snap["run_id"] = run_id
        publish_atomic(
            self.directory / SLO_NAME,
            json.dumps(snap, sort_keys=True).encode(),
        )

    def record_numerics(
        self, records, *, run_id: Optional[str] = None
    ) -> None:
        """Append per-epoch numerics records (the serialized sketches
        of :func:`..numerics.sketch_records`) to ``numerics.jsonl``,
        each stamped with `run_id`. Merged by the engine-free
        :func:`..numerics.numerics_identity`, newest wins — so the
        stream SURVIVES a failed/resumed sweep exactly like
        ``costs.jsonl``: a resumed run's bundle keeps the prior run's
        records for units it never re-executed, and a re-executed
        unit's capture replaces its prior line instead of duplicating
        it — even when the retry landed on a DIFFERENT rung (a stale
        other-engine primary left behind would mispair against later
        canaries)."""
        from yuma_simulation_tpu.telemetry.numerics import (
            numerics_identity,
        )
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        lines = []
        for rec in records:
            line = dict(rec)
            if run_id is not None:
                line["run_id"] = run_id
            lines.append(line)
        if not lines and not (self.directory / NUMERICS_NAME).exists():
            return
        path = self.directory / NUMERICS_NAME
        merged: dict[tuple, dict] = {}
        for rec in _read_jsonl(path) + lines:
            merged[numerics_identity(rec)] = rec
        publish_atomic(
            path,
            "".join(
                json.dumps(r, sort_keys=True) + "\n" for r in merged.values()
            ).encode(),
        )

    def record_costs(self, records, *, run_id: Optional[str] = None) -> None:
        """Append AOT cost records (``CostRecord`` instances or their
        ``to_json`` dicts) to ``costs.jsonl``, each stamped with
        `run_id`. Merged by ``(run_id, engine, V, M, epochs)``, newest
        wins — a resumed run's re-capture replaces its prior line
        instead of duplicating it; distinct shapes/runs accumulate."""
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        lines = []
        for rec in (
            records.values() if isinstance(records, dict) else records
        ):
            line = rec.to_json() if hasattr(rec, "to_json") else dict(rec)
            if run_id is not None:
                line["run_id"] = run_id
            lines.append(line)
        path = self.directory / COSTS_NAME
        merged: dict[tuple, dict] = {}
        for rec in _read_jsonl(path) + lines:
            merged[
                (
                    rec.get("run_id"),
                    rec.get("engine"),
                    rec.get("V"),
                    rec.get("M"),
                    rec.get("epochs"),
                )
            ] = rec
        publish_atomic(
            path,
            "".join(
                json.dumps(r, sort_keys=True) + "\n" for r in merged.values()
            ).encode(),
        )


@dataclasses.dataclass
class Bundle:
    """A loaded flight-recorder bundle (see the module docstring)."""

    directory: pathlib.Path
    spans: list
    metrics: list
    ledger: list
    report: Optional[dict] = None
    costs: list = dataclasses.field(default_factory=list)
    slo: Optional[dict] = None
    numerics: list = dataclasses.field(default_factory=list)

    def run_ids(self) -> list[str]:
        """Distinct run ids, first-seen order (spans then ledger)."""
        seen: dict[str, None] = {}
        for rec in list(self.spans) + list(self.ledger):
            rid = rec.get("run_id")
            if rid:
                seen.setdefault(rid, None)
        return list(seen)

    def latest_run_id(self) -> Optional[str]:
        ids = self.run_ids()
        return ids[-1] if ids else None


def load_bundle(directory: Union[str, pathlib.Path]) -> Bundle:
    directory = pathlib.Path(directory)

    def _json_file(name: str) -> Optional[dict]:
        path = directory / name
        if not path.exists():
            return None
        try:
            return json.loads(path.read_text())
        except json.JSONDecodeError:
            logger.warning("undecodable %s in %s", name, directory)
            return None

    return Bundle(
        directory=directory,
        spans=_read_jsonl(directory / SPANS_NAME),
        metrics=_read_jsonl(directory / METRICS_NAME),
        ledger=_read_jsonl(directory / LEDGER_NAME),
        report=_json_file(REPORT_NAME),
        costs=_read_jsonl(directory / COSTS_NAME),
        slo=_json_file(SLO_NAME),
        numerics=_read_jsonl(directory / NUMERICS_NAME),
    )


def ledger_counts(ledger: list, run_id: str) -> dict:
    """The ledger-derived twin of the :class:`SweepHealthReport` action
    counts for one run. Quarantine provenance follows the supervisor's
    resume rule: the RETURNED output carries each unit's LAST `unit_ok`
    record across the whole ledger, resumed units included."""
    this_run = [r for r in ledger if r.get("run_id") == run_id]
    oks = [r for r in this_run if r.get("event") == "unit_ok"]
    last_ok: dict = {}
    for r in ledger:
        if r.get("event") == "unit_ok" and "unit" in r:
            last_ok[r["unit"]] = r
    return {
        "stalls_killed": sum(
            1 for r in this_run if r.get("event") == "unit_stalled"
        ),
        # DISTINCT units, matching SweepHealthReport.units_requeued: a
        # unit torn twice emits one unit_requeued record per re-entry
        # but counts once in the report.
        "units_requeued": len(
            {
                r.get("unit")
                for r in this_run
                if r.get("event") == "unit_requeued"
            }
        ),
        "engine_demotions": sum(int(r.get("demotions", 0)) for r in oks),
        "mesh_shrinks": sum(int(r.get("mesh_shrinks", 0)) for r in oks),
        "lanes_quarantined": sum(
            len(r.get("quarantined", ())) for r in last_ok.values()
        ),
        "canaries_run": sum(int(r.get("canaries", 0)) for r in oks),
        "drift_events": sum(int(r.get("drifts", 0)) for r in oks),
    }


def check_bundle(bundle: Bundle) -> list[str]:
    """Consistency problems in a bundle (empty list = sound):

    - every ledger record must carry ``run_id``/``span_id`` resolving to
      a recorded span of that run (the obsreport ``--check`` gate);
    - every span's ``parent_id`` must resolve within its run — EXCEPT
      spans flagged ``remote_parent`` (a continued cross-process trace,
      :mod:`..propagation`): their parent lives in a sibling process's
      bundle and is checked by :func:`check_stitched` instead;
    - when ``report.json`` is present, its action counts must match the
      ledger-derived counts exactly (:data:`CROSS_CHECKED_COUNTS`);
    - every ``costs.jsonl`` record must name its engine, and a null
      analysis field must carry a ``reason`` (the explicit-null
      contract of :class:`..cost.CostRecord`);
    - every ``numerics.jsonl`` record must name its stream/engine/role
      and carry a per-lane fingerprint whose epoch length matches its
      declared ``epochs`` (the driftreport comparison basis — a record
      that cannot be compared is rot, not data).
    """
    from yuma_simulation_tpu.telemetry.numerics import (
        check_numerics_records,
    )

    problems: list[str] = list(check_numerics_records(bundle.numerics))
    for i, rec in enumerate(bundle.costs):
        if not rec.get("engine"):
            problems.append(f"costs[{i}] names no engine")
            continue
        for field in ("flops", "bytes_accessed", "peak_bytes"):
            if field in rec and rec[field] is None and not rec.get("reason"):
                problems.append(
                    f"costs[{i}] engine={rec['engine']} has null {field} "
                    "with no reason"
                )
    spans_by_run: dict[str, set] = {}
    for s in bundle.spans:
        spans_by_run.setdefault(s.get("run_id", ""), set()).add(
            s.get("span_id")
        )
    for s in bundle.spans:
        parent = s.get("parent_id", "")
        if s.get("remote_parent"):
            continue  # resolved across bundles by check_stitched
        if parent and parent not in spans_by_run.get(s.get("run_id", ""), ()):
            problems.append(
                f"span {s.get('span_id')} (run {s.get('run_id')}) has "
                f"unresolvable parent {parent!r}"
            )
    for i, rec in enumerate(bundle.ledger):
        event = rec.get("event", "?")
        rid, sid = rec.get("run_id"), rec.get("span_id")
        if not rid or not sid:
            problems.append(
                f"ledger[{i}] event={event} lacks run/span identity "
                f"(run_id={rid!r} span_id={sid!r})"
            )
            continue
        if sid not in spans_by_run.get(rid, ()):
            problems.append(
                f"ledger[{i}] event={event} span {sid} does not resolve "
                f"in run {rid}"
            )
    if bundle.report is not None:
        rid = bundle.report.get("run_id")
        fields = bundle.report.get("report", {})
        if rid is None:
            problems.append("report.json carries no run_id")
        else:
            derived = ledger_counts(bundle.ledger, rid)
            for key in CROSS_CHECKED_COUNTS:
                if key in fields and int(fields[key]) != int(derived[key]):
                    problems.append(
                        f"report.{key}={fields[key]} but the ledger "
                        f"derives {derived[key]} for run {rid}"
                    )
    return problems


def merge_bundles(bundles, directory=None) -> Bundle:
    """The UNION of several sibling bundles (one per process of a
    distributed run) as one logical bundle: spans/ledger/metrics/costs
    concatenated, deduped by identity, time-ordered — what the stitched
    cross-process timeline renders. `report`/`slo` keep the first
    non-None (the driver's, by caller convention)."""
    spans: dict[tuple, dict] = {}
    ledger: list = []
    metrics: list = []
    costs: list = []
    numerics: list = []
    report = None
    slo = None
    for b in bundles:
        for s in b.spans:
            spans.setdefault((s.get("run_id"), s.get("span_id")), s)
        ledger.extend(b.ledger)
        metrics.extend(b.metrics)
        costs.extend(b.costs)
        numerics.extend(b.numerics)
        if report is None:
            report = b.report
        if slo is None:
            slo = b.slo
    ledger.sort(key=lambda r: float(r.get("t") or 0.0))
    return Bundle(
        directory=pathlib.Path(directory) if directory else pathlib.Path("."),
        spans=sorted(
            spans.values(), key=lambda s: float(s.get("t_start") or 0.0)
        ),
        metrics=metrics,
        ledger=ledger,
        report=report,
        costs=costs,
        slo=slo,
        numerics=numerics,
    )


def check_stitched(bundles) -> list[str]:
    """The cross-process half of the orphan-span gate: over the UNION of
    sibling bundles, every span flagged ``remote_parent`` must resolve
    to a recorded span of the same run in SOME bundle, and every parent
    chain must terminate at a true root (empty ``parent_id``) without a
    cycle. A span whose remote parent no sibling recorded is an orphan —
    a tampered, truncated, or mis-propagated trace — and fails the
    check. Empty list = one sound stitched trace."""
    bundles = list(bundles)
    by_run: dict[str, dict[str, dict]] = {}
    for b in bundles:
        for s in b.spans:
            rid, sid = s.get("run_id", ""), s.get("span_id")
            if sid:
                by_run.setdefault(rid, {})[sid] = s
    problems: list[str] = []
    for rid, spans in sorted(by_run.items()):
        for sid, s in sorted(spans.items()):
            parent = s.get("parent_id", "")
            if parent and parent not in spans:
                problems.append(
                    f"span {sid} (run {rid}) is an orphan: parent "
                    f"{parent!r} resolves in no sibling bundle"
                )
        # Chain termination: walk each span to a root, bounded by the
        # span count so a cycle cannot hang the gate.
        for sid in sorted(spans):
            cur, hops = sid, 0
            while cur and hops <= len(spans):
                parent = spans[cur].get("parent_id", "")
                if not parent or parent not in spans:
                    break
                cur = parent
                hops += 1
            if hops > len(spans):
                problems.append(
                    f"span {sid} (run {rid}) sits on a parent cycle"
                )
    return problems


def build_timeline(bundle: Bundle, run_id: str) -> dict:
    """One run's recovery timeline: the span tree (children in start
    order) with each span's ledger records attached.

    Returns ``{"run_id", "spans": {span_id: span}, "roots": [span_id],
    "children": {span_id: [span_id]}, "records": {span_id: [ledger
    record]}}`` — everything obsreport needs to render, nothing
    presentation-specific."""
    spans = {
        s["span_id"]: s
        for s in bundle.spans
        if s.get("run_id") == run_id and s.get("span_id")
    }
    children: dict[str, list] = {sid: [] for sid in spans}
    roots: list[str] = []
    for sid, s in spans.items():
        parent = s.get("parent_id", "")
        if parent and parent in spans:
            children[parent].append(sid)
        else:
            roots.append(sid)

    def start(sid: str) -> float:
        return float(spans[sid].get("t_start") or 0.0)

    for sid in children:
        children[sid].sort(key=start)
    roots.sort(key=start)
    records: dict[str, list] = {}
    for rec in bundle.ledger:
        if rec.get("run_id") != run_id:
            continue
        records.setdefault(rec.get("span_id", ""), []).append(rec)
    for recs in records.values():
        recs.sort(key=lambda r: float(r.get("t") or 0.0))
    return {
        "run_id": run_id,
        "spans": spans,
        "roots": roots,
        "children": children,
        "records": records,
    }
