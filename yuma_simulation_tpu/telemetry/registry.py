"""The telemetry name registry: every structured event and metric name.

Before PR 11 the only "registry" was prose — the well-known-series
table in :mod:`.metrics`'s docstring — and it had drifted: nine live
series (the drift counters, the serve canary counters, the SLO burn
gauges, ``device_bytes_in_use``) existed nowhere in the documented
contract, and nothing would have caught a typo'd ``log_event`` name
until an operator's grep came back empty mid-incident. This module is
the checked replacement:

- every ``log_event`` / ledger event name the package emits is declared
  in :data:`EVENTS`, every ``counter``/``gauge``/``histogram`` name in
  :data:`METRICS`;
- each entry names its **consumers** — report tools
  (``obsreport``/``sloreport``/``driftreport``) or package modules
  (dotted, e.g. ``fabric.health``) that read the name back — or carries
  an explicit ``operator_reason`` saying why a grep-only record earns
  its place;
- ``tools/jaxlint`` cross-checks all three directions statically
  (JX201: emitted-but-undeclared, JX202: undeclared metric, JX203:
  declared consumer that never references the name / declared entry
  nothing emits), so the registry cannot rot the way the docstring
  table did.

Kept import-light (stdlib only, no jax) so the linter's fallback loader
and standalone tooling can consume it without the package's runtime
dependencies; the dataclasses double as runtime introspection for
tests (:func:`declared_events`, :func:`validate_registry`).

Declarations must stay *literal* (plain string keys, ``EventSpec`` /
``MetricSpec`` calls with constant arguments): jaxlint parses this file
with ``ast``, it never imports it.
"""

from __future__ import annotations

import dataclasses

#: Report tools (under ``tools/``) that may be named as consumers.
REPORT_TOOLS = ("obsreport", "sloreport", "driftreport", "incidentreport")


@dataclasses.dataclass(frozen=True)
class EventSpec:
    """One declared structured-event name.

    ``consumers`` lists who reads the name back: a report tool (bare
    name from :data:`REPORT_TOOLS`) or a package module (dotted path
    under ``yuma_simulation_tpu``). Events nobody consumes by name must
    say why they are worth emitting in ``operator_reason`` — "somebody
    might grep it" is exactly the claim the registry forces into
    review."""

    summary: str
    consumers: tuple = ()
    operator_reason: str = ""


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One declared metric series (kind pinned so a counter cannot
    silently become a gauge across a refactor)."""

    kind: str  # "counter" | "gauge" | "histogram" | "sketch"
    summary: str
    consumers: tuple = ()
    operator_reason: str = ""


EVENTS = {
    # -- engine ladder / watchdog / quarantine (resilience) -------------
    "engine_retry": EventSpec(
        "same-rung retry with backoff (resilience.retry.run_ladder)",
        operator_reason="recovery forensics: one greppable record per "
        "burned attempt; counted via the engine_retries metric",
    ),
    "engine_demoted": EventSpec(
        "ladder demotion onto a lower engine rung",
        operator_reason="recovery forensics; counted via the "
        "engine_demotions metric obsreport reconciles",
    ),
    "engine_stalled": EventSpec(
        "watchdog deadline kill of a hung dispatch",
        operator_reason="incident forensics; counted via stalls_killed",
    ),
    "sweep_supervised": EventSpec(
        "supervised sweep finished (one summary record per sweep)",
        operator_reason="sweep-level summary line for operator greps; "
        "per-unit accounting rides unit_ok records",
    ),
    "unit_ok": EventSpec(
        "one sweep unit finished and published",
        consumers=(
            "obsreport",
            "fabric.health",
            "telemetry.flight",
            "resilience.supervisor",
        ),
    ),
    "unit_failed": EventSpec(
        "one sweep unit exhausted every recovery path",
        operator_reason="terminal per-unit failure record; resumed "
        "sweeps skip completed units via unit_ok, failures re-run",
    ),
    "unit_retry": EventSpec(
        "one sweep unit re-dispatched after a retryable failure",
        operator_reason="per-unit recovery forensics between the "
        "attempt spans",
    ),
    "unit_requeued": EventSpec(
        "one sweep unit pushed back onto the work queue",
        consumers=("telemetry.flight",),
    ),
    "unit_stalled": EventSpec(
        "one sweep unit killed by the deadline watchdog",
        consumers=("telemetry.flight",),
    ),
    "unit_canary": EventSpec(
        "cross-engine numerics canary re-execution for one unit",
        operator_reason="canary audit trail; verdicts feed the "
        "engine_drift_ok SLO stream and the engine_drift event",
    ),
    "canary_failed": EventSpec(
        "a numerics canary re-execution itself errored (no verdict)",
        operator_reason="canary infrastructure failure is not drift; "
        "record keeps the no-verdict case auditable",
    ),
    "engine_drift": EventSpec(
        "CONFIRMED cross-engine numerics drift (bitwise divergence "
        "localized to its first epoch)",
        consumers=("telemetry.slo", "serve.service"),
        operator_reason="the typed incident record; gates ride the "
        "engine_drift_ok SLO stream and driftreport's numerics.jsonl "
        "comparison",
    ),
    "checkpoint_chunk_requeued": EventSpec(
        "corrupt/torn checkpoint chunk detected and requeued",
        operator_reason="crash-recovery forensics for resumed sweeps",
    ),
    "fault_injected": EventSpec(
        "deterministic fault armed by a chaos drill",
        operator_reason="drill forensics: pairs each injected fault "
        "with the recovery records it provoked",
    ),
    # -- dispatch planning / memory / mesh -------------------------------
    "dispatch_planned": EventSpec(
        "one DispatchPlan resolved (engine rung, bucket, memory plan)",
        operator_reason="DEBUG-level; the plan summary rides span "
        "attrs, which obsreport renders per request/unit",
    ),
    "preflight_rejected": EventSpec(
        "analytic HBM preflight rejected a dispatch before compile",
        operator_reason="capacity forensics; the typed "
        "HBMPreflightError carries the same payload to the caller",
    ),
    "mesh_degraded": EventSpec(
        "elastic mesh shrank after device loss",
        operator_reason="counted via mesh_shrinks which obsreport "
        "reconciles; record carries the lost device ids",
    ),
    "distributed_init_failed": EventSpec(
        "multi-host jax.distributed initialization failed",
        operator_reason="pod-bringup forensics (single-host fallback "
        "continues)",
    ),
    "epoch_rate": EventSpec(
        "one throughput measurement (epochs/s with dispersion)",
        operator_reason="bench forensics; the epochs_per_sec gauge is "
        "the machine-readable twin",
    ),
    # -- fleet fabric -----------------------------------------------------
    "host_started": EventSpec(
        "fleet host joined the sweep",
        consumers=("fabric.health",),
    ),
    "host_finished": EventSpec(
        "fleet host drained its queue and published its tallies",
        consumers=("fabric.health",),
    ),
    "host_lost": EventSpec(
        "fleet host declared dead (lease expired, no heartbeat)",
        consumers=("fabric.health",),
    ),
    "fleet_host_finished": EventSpec(
        "log twin of the host_finished ledger record",
        operator_reason="one INFO line per finished host for operator "
        "tails; the ledger record is the accounted copy",
    ),
    "unit_claimed": EventSpec(
        "fleet unit lease claimed",
        consumers=("fabric.health",),
    ),
    "unit_stolen": EventSpec(
        "fleet unit lease stolen from a stalled host",
        consumers=("fabric.health",),
    ),
    "unit_abandoned": EventSpec(
        "fleet unit abandoned after repeated steal generations",
        consumers=("fabric.health",),
    ),
    "unit_duplicate": EventSpec(
        "fleet unit result published twice (at-most-once collision)",
        consumers=("fabric.health",),
    ),
    "lease_stolen": EventSpec(
        "lease-level steal detail (inode generation handoff)",
        operator_reason="steal forensics below the unit_stolen ledger "
        "record",
    ),
    # -- serving tier ----------------------------------------------------
    "request_done": EventSpec(
        "one serve request completed (any outcome)",
        operator_reason="per-request ledger record; obsreport renders "
        "serve bundles span-by-span, metrics carry the aggregates",
    ),
    "request_shed": EventSpec(
        "one serve request shed (tenant quota or queue bound)",
        operator_reason="shed forensics; serve_requests_shed is the "
        "reconciled aggregate",
    ),
    "canary_ok": EventSpec(
        "serve background canary tick compared bitwise clean",
        operator_reason="canary audit trail on the serve ledger; "
        "drift flips engine_drift instead",
    ),
    "serve_warmed": EventSpec(
        "serve warmup finished (buckets compiled before first request)",
        operator_reason="cold-start forensics; compile cost rides the "
        "compile_seconds histogram and cold_start SLO",
    ),
    "serve_closed": EventSpec(
        "serve service closed and published its flight bundle",
        operator_reason="shutdown marker closing the request ledger",
    ),
    # -- horizontal scale-out (serve.router / serve.worker) --------------
    "worker_spawned": EventSpec(
        "one pool worker process spawned (startup or SLO-burn "
        "autoscale); carries the reason and the worker's AOT build "
        "count (zero when the shared executable cache warmed it)",
        consumers=("obsreport",),
    ),
    "worker_retired": EventSpec(
        "one pool worker retired gracefully (drain + bundle publish + "
        "slot release)",
        consumers=("obsreport",),
    ),
    "worker_lost": EventSpec(
        "a worker observed dead on a forward leg (connection "
        "reset/refused mid-request) — routing stops considering it "
        "before its lease even expires",
        consumers=("obsreport",),
    ),
    "request_rerouted": EventSpec(
        "one forward leg moved off a lost worker onto a survivor "
        "(the client sees the survivor's answer, never the reset)",
        consumers=("obsreport",),
    ),
    "worker_spawning": EventSpec(
        "router forked a worker process onto a free slot (precedes "
        "the ledgered worker_spawned, which waits for readiness)",
        operator_reason="spawn forensics: pins the pid/slot when a "
        "worker dies before ever advertising",
    ),
    "worker_ready": EventSpec(
        "worker claimed its slot lease and began heartbeating ads",
        operator_reason="startup marker in the worker's own log; the "
        "router-side worker_spawned record is the reconciled event",
    ),
    "worker_lease_lost": EventSpec(
        "worker's own slot lease expired under it (missed heartbeats) "
        "— it must stop serving rather than split-brain the slot",
        operator_reason="incident forensics for the worker side of a "
        "partition; the router side rides worker_lost",
    ),
    "worker_stopped": EventSpec(
        "worker drained, published its bundle, and released its slot",
        operator_reason="shutdown marker closing the worker's log",
    ),
    "router_stopped": EventSpec(
        "router closed: pool retired, merged ingress bundle published",
        operator_reason="shutdown marker closing the router's ledger",
    ),
    "autoscale_up": EventSpec(
        "autoscaler spawned one worker on an SLO fast burn",
        operator_reason="capacity forensics; the ledgered "
        "worker_spawned record carries the same reason string",
    ),
    "autoscale_down": EventSpec(
        "autoscaler retired one idle worker (youngest-first)",
        operator_reason="capacity forensics; the ledgered "
        "worker_retired record is the reconciled event",
    ),
    "breaker_tripped": EventSpec(
        "circuit breaker opened an engine rung fleet-wide",
        operator_reason="breaker forensics; serve_breaker_trips / "
        "serve_breaker_open are the reconciled aggregates",
    ),
    "breaker_half_open": EventSpec(
        "circuit breaker probing a tripped rung",
        operator_reason="breaker state-machine forensics",
    ),
    "breaker_probe_aborted": EventSpec(
        "half-open probe failed; rung re-opened",
        operator_reason="breaker state-machine forensics",
    ),
    "breaker_closed": EventSpec(
        "circuit breaker closed a recovered rung",
        operator_reason="breaker state-machine forensics",
    ),
    # -- SLO engine ------------------------------------------------------
    "slo_alert": EventSpec(
        "burn-rate alert entered fast/slow burn",
        consumers=("serve.service",),
    ),
    "slo_recovered": EventSpec(
        "burn-rate alert recovered to ok",
        consumers=("serve.service",),
    ),
    # -- AOT executable cache (simulation.aot) ---------------------------
    "executable_cache_hit": EventSpec(
        "one published executable deserialized and dispatched (cold "
        "start skipped a compile)",
        operator_reason="cold-start forensics: one record per program "
        "load; the executable_cache_hits counter is the reconciled "
        "aggregate the CI cold-start lane asserts on via "
        "cache_stats.json",
    ),
    "executable_cache_miss": EventSpec(
        "no loadable artifact for this program (reason: absent / "
        "corrupt / torn / undeserializable) — dispatch requeued to JIT",
        operator_reason="typed miss taxonomy: a corrupt or truncated "
        "artifact must surface as a greppable reason, never a crash or "
        "a silent slow start",
    ),
    "executable_cache_stale": EventSpec(
        "artifacts for this exact program exist only under another "
        "toolchain/device — rebuilt instead of misexecuted",
        operator_reason="upgrade forensics: a jax/jaxlib bump or a "
        "device swap shows up as stale misses, the signal to re-warm "
        "the cache",
    ),
    # -- scenario foundry ------------------------------------------------
    "scenario_compiled": EventSpec(
        "one foundry ScenarioSpec materialized to dense Scenario arrays",
        operator_reason="DEBUG-level log-stream provenance per generated "
        "scenario; the scenarios_generated counter (obsreport-rendered) "
        "is the machine-readable process-lifetime aggregate",
    ),
    "metagraph_loaded": EventSpec(
        "one metagraph snapshot file ingested (netuid/block/shape)",
        operator_reason="ingestion audit trail on the log stream: which "
        "snapshot file fed which generated suite (grep event=)",
    ),
    # -- chain replay (replay/) ------------------------------------------
    "whatif_served": EventSpec(
        "one what-if executed against a cached baseline (ledger record "
        "carries tenant, cache hit, resume epoch, suffix vs full epochs)",
        consumers=("obsreport",),
    ),
    "state_cache_hit": EventSpec(
        "a what-if resumed from a cached epoch-state checkpoint "
        "(suffix-sized re-simulation)",
        operator_reason="per-resolve forensics on the log stream; the "
        "state_cache_hits counter is the reconciled aggregate the "
        "replay drill and obsreport's replay section read",
    ),
    "state_cache_miss": EventSpec(
        "a what-if found no usable cached epoch state (reason: baseline "
        "not built / no checkpoint before the perturb epoch / state "
        "unreadable) — full-trajectory re-simulation",
        operator_reason="typed miss taxonomy on the log stream; the "
        "state_cache_misses counter is the reconciled aggregate",
    ),
    # -- continuous replay controller (replay.controller) ----------------
    "subnet_ingested": EventSpec(
        "the controller observed fresh archive entries for a subnet "
        "(record carries netuid, new blocks, latest block)",
        consumers=("obsreport",),
    ),
    "window_swept": EventSpec(
        "one incremental (subnet x variant) window swept, published and "
        "baseline-extended (record carries netuid, version, block span, "
        "epoch span, suffix vs full epochs)",
        consumers=("obsreport",),
    ),
    "watermark_advanced": EventSpec(
        "a durable per-(subnet x variant) watermark moved forward after "
        "a window's fleet results published (the at-least-once sweep / "
        "exactly-once publication commit point)",
        consumers=("obsreport",),
    ),
    "subnet_stalled": EventSpec(
        "a subnet's archive stopped appending past the stall deadline; "
        "the controller demoted it to the slow poll tier",
        consumers=("obsreport",),
    ),
    "subnet_quarantined": EventSpec(
        "a corrupt or truncated snapshot blob was quarantined (typed "
        "reason; the entry is excluded and the subnet keeps draining)",
        consumers=("obsreport",),
    ),
    # -- continuous telemetry plane (telemetry.flight / telemetry.ops) ----
    "segment_sealed": EventSpec(
        "the flight recorder's live rotation segment hit a size/age "
        "bound (or was sealed at close) and published its seal.json "
        "(record carries segment name, bytes, run ids)",
        consumers=("obsreport",),
    ),
    "segments_compacted": EventSpec(
        "retention reclaimed sealed segments past the policy's byte "
        "bound and merged them into the compacted.json tombstone that "
        "exempts their runs from span checks",
        consumers=("obsreport",),
    ),
    "profile_started": EventSpec(
        "an on-demand device-profiling window opened (POST "
        "/debug/profile, SweepSupervisor profile_every, or the replay "
        "controller's --profile-window; record carries mode, artifact "
        "dir, deadline)",
        consumers=("obsreport",),
    ),
    "profile_published": EventSpec(
        "a profiling window closed and its trace artifact was "
        "registered into the bundle's profiles.jsonl",
        consumers=("obsreport",),
    ),
    # -- incident intelligence (telemetry.incident / telemetry.anomaly) ---
    "anomaly_detected": EventSpec(
        "a robust detector (MAD / rate-of-change / counter-stall / "
        "saturation) fired on a metric time series; record carries "
        "kind, series, value, baseline, threshold, window",
        consumers=("incidentreport", "obsreport", "telemetry.incident"),
    ),
    "incident_opened": EventSpec(
        "the correlation engine opened an incident around a typed "
        "fault ledger event (record carries incident id, cause_class, "
        "cause_event, subject); full state rides incidents.jsonl",
        consumers=("incidentreport", "obsreport", "fabric.health"),
    ),
    "incident_resolved": EventSpec(
        "an open incident's cause class observed its recovery event "
        "(record carries incident id, resolution)",
        consumers=("incidentreport", "obsreport", "fabric.health"),
    ),
    "controller_restarted": EventSpec(
        "a restarting replay controller found a stale open-run marker "
        "from a prior incarnation that never closed (SIGKILL/crash) — "
        "the typed cause behind process-loss incidents",
        consumers=("incidentreport", "telemetry.incident"),
    ),
}


METRICS = {
    # -- engine / sweep core --------------------------------------------
    "epochs_total": MetricSpec(
        "counter", "simulated epochs (lanes x E), from the epoch-rate "
        "reporters",
    ),
    "epochs_per_sec": MetricSpec(
        "gauge", "last observed throughput (event=epoch_rate twin)",
        consumers=("obsreport",),
    ),
    "epochs_per_sec_cv": MetricSpec(
        "gauge", "timing dispersion (CV) of the last rate",
    ),
    "compile_seconds": MetricSpec(
        "histogram", "wall seconds of sentinel regions that added "
        "jit-cache entries (compile-time upper bound)",
    ),
    "recompiles": MetricSpec(
        "counter", "new jit-cache entries observed by "
        "RecompilationSentinel regions",
    ),
    "engine_retries": MetricSpec(
        "counter", "same-rung ladder retries",
    ),
    "engine_demotions": MetricSpec(
        "counter", "engine-ladder demotions",
        consumers=("obsreport",),
    ),
    "stalls_killed": MetricSpec(
        "counter", "watchdog deadline kills",
        consumers=("obsreport",),
    ),
    "mesh_shrinks": MetricSpec(
        "counter", "elastic mesh degradations",
        consumers=("obsreport",),
    ),
    "quarantined_lanes": MetricSpec(
        "counter", "non-finite lanes masked by the quarantine guard",
    ),
    "checkpoint_bytes": MetricSpec(
        "counter", "bytes of published checkpoint chunk snapshots",
    ),
    # -- device telemetry ------------------------------------------------
    "device_peak_bytes": MetricSpec(
        "gauge", "peak device memory at last sample (None-safe on CPU)",
    ),
    "device_bytes_in_use": MetricSpec(
        "gauge", "device memory in use at last sample",
    ),
    "live_buffers": MetricSpec(
        "gauge", "live jax.Array count at last sample",
    ),
    # -- numerics flight recorder ---------------------------------------
    "numerics_canaries": MetricSpec(
        "counter", "cross-engine canary re-executions",
    ),
    "engine_drift_total": MetricSpec(
        "counter", "canary comparisons that CONFIRMED drift",
    ),
    "engine_drift_expected": MetricSpec(
        "counter", "canary drift crossings stamped expected (the "
        "documented u16-fallback pairing class)",
    ),
    # -- serving tier ----------------------------------------------------
    "serve_requests_total": MetricSpec(
        "counter", "serving-tier requests handled (any outcome)",
    ),
    "serve_queue_depth": MetricSpec(
        "gauge", "run-queue occupancy right now",
    ),
    "serve_requests_shed": MetricSpec(
        "counter", "429-shed requests (tenant quota or queue bound)",
    ),
    "serve_admission_rejected": MetricSpec(
        "counter", "typed admission rejections (pre-compile)",
    ),
    "serve_coalesced_lanes": MetricSpec(
        "counter", "requests donor-packed into a shared dispatch",
    ),
    "serve_breaker_trips": MetricSpec(
        "counter", "circuit-breaker rung trips",
    ),
    "serve_breaker_open": MetricSpec(
        "gauge", "engine rungs currently tripped open",
    ),
    "serve_request_seconds": MetricSpec(
        "histogram", "request wall time, admission to reply",
    ),
    "serve_canary_ticks": MetricSpec(
        "counter", "background numerics-canary bucket re-executions",
    ),
    "serve_canary_drift": MetricSpec(
        "counter", "serve canary comparisons that confirmed drift",
    ),
    # -- horizontal scale-out (serve.router) -----------------------------
    "serve_workers_live": MetricSpec(
        "gauge", "live serve workers behind the router (fresh lease + "
        "advertisement) right now",
        consumers=("obsreport",),
    ),
    "serve_reroutes_total": MetricSpec(
        "counter", "forward legs rerouted off a lost worker onto a "
        "survivor",
        consumers=("obsreport",),
    ),
    "affinity_hits_total": MetricSpec(
        "counter", "requests the claim scorer placed on a worker "
        "already holding useful state (cache prefix or warm bucket)",
        consumers=("obsreport",),
    ),
    # -- AOT executable cache (simulation.aot) ---------------------------
    "executable_cache_hits": MetricSpec(
        "counter", "published executables deserialized from the cache "
        "(compiles skipped)",
    ),
    "executable_cache_misses": MetricSpec(
        "counter", "cache lookups with no loadable artifact (absent or "
        "corrupt — dispatch requeued to JIT)",
    ),
    "executable_cache_stale": MetricSpec(
        "counter", "lookups that found only other-toolchain/device "
        "artifacts for the program",
    ),
    "executable_cache_builds": MetricSpec(
        "counter", "programs AOT-exported and published after a miss "
        "(true compiles, counted by RecompilationSentinel budgets)",
    ),
    # -- scenario foundry ------------------------------------------------
    "scenarios_generated": MetricSpec(
        "counter", "foundry-generated scenarios (DSL compiles + "
        "metagraph ingestions + adversarial builds)",
        consumers=("obsreport",),
    ),
    # -- chain replay (replay/) ------------------------------------------
    "state_cache_hits": MetricSpec(
        "counter", "what-if suffix resumes served from a cached epoch "
        "state",
        consumers=("obsreport",),
    ),
    "state_cache_misses": MetricSpec(
        "counter", "what-if requests with no usable cached epoch state "
        "(full re-simulation)",
        consumers=("obsreport",),
    ),
    "replay_suffix_epochs_saved": MetricSpec(
        "counter", "epochs cached carries let what-ifs skip "
        "re-simulating (suffix-vs-full savings)",
        consumers=("obsreport",),
    ),
    # -- continuous replay controller (replay.controller) ----------------
    "replay_staleness_seconds": MetricSpec(
        "gauge", "per-cycle worst-case age of the oldest unswept "
        "archive suffix across live subnets (freshness SLO input)",
        consumers=("obsreport",),
    ),
    "subnets_live": MetricSpec(
        "gauge", "subnets on the fast poll tier (not stalled)",
        consumers=("obsreport",),
    ),
    "windows_swept_total": MetricSpec(
        "counter", "incremental (subnet x variant) windows published by "
        "the continuous replay controller",
        consumers=("obsreport",),
    ),
    "snapshots_quarantined_total": MetricSpec(
        "counter", "corrupt/truncated snapshot blobs quarantined by the "
        "controller",
        consumers=("obsreport",),
    ),
    # -- continuous telemetry plane (telemetry.flight / telemetry.slo) ----
    "telemetry_segments_total": MetricSpec(
        "counter", "flight-recorder segments sealed by rotation",
        consumers=("obsreport",),
    ),
    "telemetry_bytes_retained": MetricSpec(
        "gauge", "bytes currently retained across sealed rotation "
        "segments (post-compaction)",
        consumers=("obsreport",),
    ),
    "dispatch_seconds": MetricSpec(
        "sketch", "always-on per-(engine rung x shape bucket x backend) "
        "dispatch wall-time quantile sketches (DispatchStats), riding "
        "metrics lines as the dispatch_sketches field; "
        "tools/perfattrib.py joins them against cost/roofline records",
        consumers=("obsreport",),
    ),
    # -- SLO engine ------------------------------------------------------
    "slo_alerts_total": MetricSpec(
        "counter", "burn-rate alert transitions (any direction)",
    ),
    "slo_fast_burn_active": MetricSpec(
        "gauge", "SLOs currently in fast burn",
    ),
    "slo_slow_burn_active": MetricSpec(
        "gauge", "SLOs currently in slow burn",
    ),
    # -- incident intelligence (telemetry.incident) ----------------------
    "incidents_open": MetricSpec(
        "gauge", "correlated incidents currently open in this bundle "
        "(also the open-incident count /healthz reports)",
        consumers=("incidentreport", "serve.service"),
    ),
    "anomalies_total": MetricSpec(
        "counter", "detector firings ledgered as anomaly_detected",
        consumers=("incidentreport", "obsreport"),
    ),
}


def declared_events() -> frozenset:
    return frozenset(EVENTS)


def declared_metrics() -> frozenset:
    return frozenset(METRICS)


def validate_registry() -> list:
    """Runtime twin of jaxlint's JX203 shape checks: every entry either
    names consumers or justifies itself, kinds are legal, and consumer
    names look resolvable. Returns a list of problem strings (empty =
    healthy) — tests assert on it so a bad edit fails fast even before
    the lint gate runs."""
    problems = []
    for name, spec in EVENTS.items():
        if not spec.consumers and not spec.operator_reason:
            problems.append(
                f"event {name!r}: no consumers and no operator_reason"
            )
    for name, spec in METRICS.items():
        # "sketch" (0.23.0): a quantile-sketch family riding metrics
        # lines (dispatch_seconds) rather than a registry series.
        if spec.kind not in ("counter", "gauge", "histogram", "sketch"):
            problems.append(f"metric {name!r}: unknown kind {spec.kind!r}")
    return problems
