"""Cross-process trace propagation: one trace from serve to fleet.

PR 4's telemetry is strictly process-local: every process mints its own
:class:`..runctx.RunContext`, so a serve request executed by a fleet
host — or a sweep fanned out across simulated hosts — shatters into
disconnected span trees that no tool can stitch back together. This
module is the identity carrier between processes:

- :class:`TraceContext` — the serializable ``(run_id, span_id,
  baggage)`` triple, W3C-traceparent-style on the wire
  (``00-<run_id>-<span_id>-01`` + a ``baggage`` ``k=v,k=v`` companion):
  the HTTP client/server pair exchange it as headers, the fleet store
  carries it in the write-once manifest and each lease record, and
  subprocess hosts inherit it through the environment
  (``YUMA_TRACEPARENT`` / ``YUMA_BAGGAGE``);
- :func:`current_trace_context` — capture the active run + innermost
  span as a context to hand downstream;
- :func:`child_run` / :func:`continue_trace` — the receiving side:
  a :class:`..runctx.RunContext` that CONTINUES the caller's run
  (same ``run_id``, spans parented under the caller's span, ids minted
  under a process-unique prefix so sibling processes can never collide)
  instead of minting an orphan root.

A continued run's root spans are flagged ``remote_parent`` in their
records: the single-bundle consistency check
(:func:`..flight.check_bundle`) exempts them from local parent
resolution, and the stitched multi-bundle check
(:func:`..flight.check_stitched`) demands they resolve in SOME sibling
bundle — an orphan whose parent no process recorded is exactly the
corruption ``obsreport --check`` must fail on.

Everything here is host-side string/dict bookkeeping: zero compiles,
zero reads from traced code, and malformed headers/env values parse to
``None`` (propagation is best-effort identity, never a crash).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import os
import uuid
from typing import Iterator, Mapping, Optional

from yuma_simulation_tpu.telemetry.runctx import (
    RunContext,
    current_run,
    current_span,
)

#: Wire names (HTTP headers, lowercase per RFC 9110 field-name rules).
TRACEPARENT_HEADER = "traceparent"
BAGGAGE_HEADER = "baggage"
#: Environment names for subprocess propagation (simulated fleet hosts).
TRACEPARENT_ENV = "YUMA_TRACEPARENT"
BAGGAGE_ENV = "YUMA_BAGGAGE"

_VERSION = "00"
_FLAGS = "01"


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One point in a distributed trace: the run to continue and the
    span to parent under, plus free-form string baggage (tenant,
    request ids — identity only, never payload)."""

    run_id: str
    span_id: str = ""
    baggage: tuple = ()

    # -- wire form ------------------------------------------------------

    def to_traceparent(self) -> str:
        """``00-<run_id>-<span_id>-01``. The ``run_id`` may contain
        dashes (``run-ab12...``); the parser re-joins the middle fields,
        which is why span ids must never contain one (enforced at
        minting, :class:`..runctx.RunContext`)."""
        return "-".join(
            (_VERSION, self.run_id, self.span_id or "root", _FLAGS)
        )

    def to_baggage(self) -> str:
        return ",".join(f"{k}={v}" for k, v in self.baggage)

    @classmethod
    def from_traceparent(
        cls,
        header: Optional[str],
        baggage: Optional[str] = None,
    ) -> Optional["TraceContext"]:
        """Parse the wire form; ``None`` for anything malformed (an
        unparseable header downgrades to a fresh local trace, never an
        error a client can trigger)."""
        if not header or not isinstance(header, str):
            return None
        parts = header.strip().split("-")
        if len(parts) < 4 or parts[0] != _VERSION:
            return None
        span_id = parts[-2]
        run_id = "-".join(parts[1:-2])
        if not run_id or not span_id:
            return None
        bags: list[tuple] = []
        if baggage:
            for item in baggage.split(","):
                if "=" not in item:
                    continue
                k, v = item.split("=", 1)
                k, v = k.strip(), v.strip()
                if k:
                    bags.append((k, v))
        return cls(
            run_id=run_id,
            span_id="" if span_id == "root" else span_id,
            baggage=tuple(bags),
        )

    # -- env form (subprocess hosts) ------------------------------------

    def to_env(self) -> dict:
        env = {TRACEPARENT_ENV: self.to_traceparent()}
        if self.baggage:
            env[BAGGAGE_ENV] = self.to_baggage()
        return env

    @classmethod
    def from_env(
        cls, environ: Optional[Mapping[str, str]] = None
    ) -> Optional["TraceContext"]:
        environ = os.environ if environ is None else environ
        return cls.from_traceparent(
            environ.get(TRACEPARENT_ENV), environ.get(BAGGAGE_ENV)
        )

    # -- manifest form (fleet stores) -----------------------------------

    def to_manifest(self) -> dict:
        """The fleet-manifest field (:meth:`..fabric.store.FleetStore
        .ensure_manifest` carries it under ``"trace"``, excluded from
        the write-once identity check: the trace names WHO drove the
        sweep, not WHAT the sweep is)."""
        rec = {"traceparent": self.to_traceparent()}
        if self.baggage:
            rec["baggage"] = self.to_baggage()
        return rec

    @classmethod
    def from_manifest(cls, manifest: Mapping) -> Optional["TraceContext"]:
        trace = manifest.get("trace") if isinstance(manifest, Mapping) else None
        if not isinstance(trace, Mapping):
            return None
        return cls.from_traceparent(
            trace.get("traceparent"), trace.get("baggage")
        )

    def with_baggage(self, **items: str) -> "TraceContext":
        merged = dict(self.baggage)
        merged.update({k: str(v) for k, v in items.items()})
        return dataclasses.replace(
            self, baggage=tuple(sorted(merged.items()))
        )


def current_trace_context(**baggage: str) -> Optional[TraceContext]:
    """The active run + innermost open span as a :class:`TraceContext`
    to hand downstream, or ``None`` outside any run. `baggage` items
    ride along (stringified)."""
    run = current_run()
    if run is None:
        return None
    s = current_span()
    ctx = TraceContext(run_id=run.run_id, span_id=s.span_id if s else "")
    return ctx.with_baggage(**baggage) if baggage else ctx


def span_prefix_for(name: str = "") -> str:
    """A process-unique span-id prefix for a continued run: stable hash
    of `name` (host ids are already process-unique) or a random nonce.
    Dash-free by construction — traceparent framing depends on it."""
    if name:
        return hashlib.sha256(name.encode()).hexdigest()[:8]
    return uuid.uuid4().hex[:8]


def child_run(ctx: TraceContext, *, prefix: str = "") -> RunContext:
    """A :class:`RunContext` continuing `ctx`'s trace in THIS process:
    same ``run_id``, span ids minted under a unique prefix, root spans
    parented under ``ctx.span_id`` (flagged ``remote_parent`` for the
    bundle checks). The caller enters/activates it as usual."""
    return RunContext(
        run_id=ctx.run_id,
        span_prefix=prefix or span_prefix_for(),
        remote_parent=ctx.span_id,
    )


@contextlib.contextmanager
def continue_trace(
    ctx: Optional[TraceContext], *, prefix: str = ""
) -> Iterator[RunContext]:
    """The receiving side's one entry point: join the already-active
    run when there is one (in-process callers keep their natural span
    nesting), continue `ctx` in a child run when given one, and fall
    back to a fresh run otherwise — :func:`..runctx.ensure_run` with a
    cross-process option."""
    run = current_run()
    if run is not None:
        yield run
        return
    if ctx is None:
        with RunContext() as run:
            yield run
        return
    with child_run(ctx, prefix=prefix) as run:
        yield run
