"""Ahead-of-time cost models: XLA cost/memory capture, rooflines, HBM
preflight.

PR 4 made the sweep stack observable at runtime (spans, metrics, flight
recorder); this module is the compile-time half. The ROADMAP north star —
"as fast as the hardware allows" — is unverifiable without knowing what
the hardware allows, and XLA already computes the answer at compile time:
``compiled.cost_analysis()`` (flops, bytes moved, transcendentals) and
``compiled.memory_analysis()`` (argument/output/temp/peak bytes). Three
layers on top of that capture:

- :func:`capture_engine_costs` — lower + AOT-compile each engine rung
  (``fused_scan_mxu`` / ``fused_scan`` / ``xla``) at a given `[E, V, M]`
  shape from ``jax.ShapeDtypeStruct`` specs (no device allocation) and
  normalize the analyses into :class:`CostRecord` lines, HLO fingerprint
  included. Backend-graceful: on CPU the fused Pallas rungs yield an
  explicit-null record with a ``reason`` instead of pretending the
  interpret-mode emulation is the chip program.
- :func:`roofline` — classify a record compute- vs memory-bound against
  a small overridable :class:`DeviceSpec` table (peak FLOP/s, HBM
  bandwidth) and predict the epochs/s ceiling the rung should be
  hitting, so BENCH numbers compare against physics, not vibes.
- :func:`preflight_hbm` — the ANALYTIC (zero-compile) footprint check
  the engine/sharding advisors run before every dispatch:
  :func:`estimate_hbm_bytes` predicts peak resident bytes from shapes
  alone, and a shape that cannot fit (e.g. 8192x131072 on a 16 GiB
  part) is rejected with a typed :class:`HBMPreflightError` and one
  ``event=preflight_rejected`` record BEFORE XLA ever starts the
  minutes-scale compile that would discover it the hard way.

Cost capture compiles programs by construction, so it is explicit-call
only (bench, perfgate, obsreport, the supervisor's opt-in) — never on
the hot path. The preflight IS on the hot path and therefore never
compiles, traces, or allocates: pure host arithmetic on shapes. The
zero-warm-repeat budgets of tests/unit/test_recompilation.py stay
authoritative.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import logging
import os
from typing import Optional, Sequence

logger = logging.getLogger(__name__)

#: The engine ladder, best rung first — mirrors
#: :data:`..resilience.retry.ENGINE_LADDER` (kept literal here so cost
#: capture does not import the resilience tier). 0.19.0 adds the
#: epoch-tiled varying-weights rungs.
ENGINE_RUNGS = (
    "fused_varying_mxu",
    "fused_varying",
    "fused_scan_mxu",
    "fused_scan",
    "xla",
)

#: Env var naming a JSON DeviceSpec override, e.g.
#: ``{"name": "lab-v5e", "peak_flops": 1.97e14,
#: "hbm_bandwidth": 8.19e11, "memory_bytes": 17179869184}``.
DEVICE_SPEC_ENV = "YUMA_TPU_DEVICE_SPEC"

#: Env var disabling the HBM preflight ("0"/"off"/"false").
PREFLIGHT_ENV = "YUMA_TPU_PREFLIGHT"

#: `[V, M]`-sized buffers the engines keep resident beyond the epoch
#: stack itself: the bonds carry, the prev-weights carry, the normalized
#: and consensus-clipped weight intermediates, plus XLA temp headroom.
#: Deliberately a round upper bound — the preflight's job is to reject
#: what CANNOT fit, not to flatter what barely might.
WORKING_SET_VM_BUFFERS = 6

#: Fraction of device memory the predicted footprint may claim before
#: the preflight rejects: XLA's allocator reserves the rest.
DEFAULT_MEMORY_FRACTION = 0.92


# ---------------------------------------------------------------------------
# Device specs


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """What one device can do: peak FLOP/s (dense matmul, native
    precision), HBM bandwidth (bytes/s), and usable memory (bytes).
    ``None`` fields mean "unknown" — every consumer degrades to a
    null/unknown verdict rather than inventing numbers (the CPU spec is
    all-None by design)."""

    name: str
    peak_flops: Optional[float] = None
    hbm_bandwidth: Optional[float] = None
    memory_bytes: Optional[int] = None


#: device_kind substring (lowercase) -> spec. Public per-chip numbers;
#: a lab with different parts overrides via ``YUMA_TPU_DEVICE_SPEC`` or
#: an explicit ``spec=`` argument. Longest key wins, so "v5 lite"
#: matches before "v5".
DEVICE_SPECS: dict[str, DeviceSpec] = {
    "v2": DeviceSpec("TPU v2", 45e12, 700e9, 8 * 2**30),
    "v3": DeviceSpec("TPU v3", 123e12, 900e9, 16 * 2**30),
    "v4": DeviceSpec("TPU v4", 275e12, 1228e9, 32 * 2**30),
    "v5 lite": DeviceSpec("TPU v5e", 197e12, 819e9, 16 * 2**30),
    "v5litepod": DeviceSpec("TPU v5e", 197e12, 819e9, 16 * 2**30),
    "v5e": DeviceSpec("TPU v5e", 197e12, 819e9, 16 * 2**30),
    "v5p": DeviceSpec("TPU v5p", 459e12, 2765e9, 95 * 2**30),
    "v5": DeviceSpec("TPU v5p", 459e12, 2765e9, 95 * 2**30),
    "v6 lite": DeviceSpec("TPU v6e", 918e12, 1640e9, 32 * 2**30),
    "v6e": DeviceSpec("TPU v6e", 918e12, 1640e9, 32 * 2**30),
    "cpu": DeviceSpec("cpu"),
}


#: Resolved-spec cache keyed on the env override value: the preflight
#: runs per dispatch and must stay pure host arithmetic — the device
#: probe (and env JSON parse) happens once per distinct override, not
#: per call. Device kind and `bytes_limit` are process-invariant.
_RESOLVED_SPECS: dict[str, DeviceSpec] = {}


def resolve_device_spec(override: Optional[DeviceSpec] = None) -> DeviceSpec:
    """The spec for the current backend: explicit `override` wins, then
    the :data:`DEVICE_SPEC_ENV` JSON override, then the
    :data:`DEVICE_SPECS` table keyed on ``device_kind`` (longest
    matching substring), then the runtime's own ``memory_stats``
    ``bytes_limit`` as a memory-only spec, then all-None."""
    if override is not None:
        return override
    env = os.environ.get(DEVICE_SPEC_ENV)
    cached = _RESOLVED_SPECS.get(env or "")
    if cached is not None:
        return cached
    spec = _resolve_device_spec_uncached(env)
    _RESOLVED_SPECS[env or ""] = spec
    return spec


def _resolve_device_spec_uncached(env: Optional[str]) -> DeviceSpec:
    if env:
        try:
            fields = json.loads(env)
            return DeviceSpec(
                name=str(fields.get("name", "env-override")),
                peak_flops=fields.get("peak_flops"),
                hbm_bandwidth=fields.get("hbm_bandwidth"),
                memory_bytes=fields.get("memory_bytes"),
            )
        except (ValueError, TypeError):
            logger.warning(
                "undecodable %s=%r ignored", DEVICE_SPEC_ENV, env
            )
    kind, bytes_limit = _probe_device()
    if kind:
        lowered = kind.lower()
        for key in sorted(DEVICE_SPECS, key=len, reverse=True):
            if key in lowered:
                found = DEVICE_SPECS[key]
                if found.memory_bytes is None and bytes_limit:
                    return dataclasses.replace(
                        found, memory_bytes=bytes_limit
                    )
                return found
    if bytes_limit:
        return DeviceSpec(name=kind or "unknown", memory_bytes=bytes_limit)
    return DeviceSpec(name=kind or "unknown")


def _probe_device() -> tuple[Optional[str], Optional[int]]:
    """(device_kind, memory_stats bytes_limit) of device 0, best-effort
    — a backend probe failure degrades to (None, None), never raises."""
    try:
        import jax

        device = jax.local_devices()[0]
        kind = getattr(device, "device_kind", None)
    except Exception:
        return None, None
    try:
        stats = device.memory_stats() or {}
        limit = stats.get("bytes_limit")
        return kind, int(limit) if limit else None
    except Exception:
        return kind, None


# ---------------------------------------------------------------------------
# AOT cost capture


@dataclasses.dataclass
class CostRecord:
    """One engine rung's compile-time cost surface at one shape. Every
    analysis field is Optional: a null carries a non-null ``reason``
    (CPU lacking the Pallas rung, a runtime not reporting a field) so a
    schema gate can tell "unmeasured, and here is why" from "forgot"."""

    engine: str
    backend: Optional[str]
    V: int
    M: int
    epochs: int
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    transcendentals: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    peak_bytes: Optional[int] = None
    #: "memory_analysis" when the runtime reported an explicit peak,
    #: "derived" when peak = arguments + outputs + temps.
    peak_bytes_source: Optional[str] = None
    generated_code_bytes: Optional[int] = None
    hlo_fingerprint: Optional[str] = None
    #: Why any of the above is null (capture failure, rung unavailable
    #: on this backend, runtime not reporting the analysis).
    reason: Optional[str] = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @property
    def flops_per_epoch(self) -> Optional[float]:
        """`flops / epochs` — a LOWER bound per epoch: XLA's cost
        analysis counts a scan body once regardless of trip count (see
        :func:`roofline`'s honesty note)."""
        if self.flops is None or self.epochs <= 0:
            return None
        return self.flops / self.epochs

    @property
    def bytes_per_epoch(self) -> Optional[float]:
        """`bytes_accessed / epochs`, same scan-amortization caveat as
        :attr:`flops_per_epoch`."""
        if self.bytes_accessed is None or self.epochs <= 0:
            return None
        return self.bytes_accessed / self.epochs


def _normalize_cost_analysis(analysis) -> dict:
    """XLA's cost analysis across jax versions: a flat dict (new), a
    list of per-computation dicts (old), or None. Returns the summed
    flat dict; only the well-known keys are consumed downstream."""
    if analysis is None:
        return {}
    entries = analysis if isinstance(analysis, (list, tuple)) else [analysis]
    merged: dict = {}
    for entry in entries:
        for key, value in (entry or {}).items():
            try:
                merged[key] = merged.get(key, 0.0) + float(value)
            except (TypeError, ValueError):
                continue
    return merged


def hlo_fingerprint(lowered, *, digits: Optional[int] = 16) -> str:
    """sha256 of a ``jax.stages.Lowered`` program's HLO text — THE
    content-address of a compiled program. One spelling, two consumers:
    the cost records here truncate it to 16 hex digits for display, and
    the AOT executable cache (:mod:`..simulation.aot`) keys its on-disk
    artifacts on the full digest (``digits=None``) so two programs whose
    HLO differs anywhere can never collide onto one executable."""
    digest = hashlib.sha256(lowered.as_text().encode()).hexdigest()
    return digest if digits is None else digest[:digits]


def capture_compiled(
    lowered, *, engine: str, V: int, M: int, epochs: int
) -> CostRecord:
    """Compile a ``jax.stages.Lowered`` and normalize its cost/memory
    analyses into a :class:`CostRecord`. Partial fields tolerated: a
    runtime that reports neither analysis still yields the HLO
    fingerprint, with ``reason`` naming what is missing."""
    import jax

    record = CostRecord(
        engine=engine, backend=jax.default_backend(), V=V, M=M, epochs=epochs
    )
    try:
        record.hlo_fingerprint = hlo_fingerprint(lowered)
    except Exception as e:
        record.reason = f"as_text failed: {e}"
    try:
        compiled = lowered.compile()
    except Exception as e:
        record.reason = f"compile failed: {_first_line(e)}"
        return record
    missing: list[str] = []
    try:
        cost = _normalize_cost_analysis(compiled.cost_analysis())
    except Exception:
        cost = {}
    if cost:
        record.flops = cost.get("flops")
        record.bytes_accessed = cost.get("bytes accessed")
        record.transcendentals = cost.get("transcendentals")
    if record.flops is None or record.bytes_accessed is None:
        missing.append("cost_analysis")
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        record.argument_bytes = _opt_int(
            getattr(mem, "argument_size_in_bytes", None)
        )
        record.output_bytes = _opt_int(
            getattr(mem, "output_size_in_bytes", None)
        )
        record.temp_bytes = _opt_int(getattr(mem, "temp_size_in_bytes", None))
        record.generated_code_bytes = _opt_int(
            getattr(mem, "generated_code_size_in_bytes", None)
        )
        explicit_peak = _opt_int(getattr(mem, "peak_memory_in_bytes", None))
        arg, out, tmp = (
            record.argument_bytes, record.output_bytes, record.temp_bytes
        )
        if explicit_peak:
            record.peak_bytes = explicit_peak
            record.peak_bytes_source = "memory_analysis"
        elif arg is not None and out is not None and tmp is not None:
            # The static program footprint — what the runtime must hold
            # simultaneously — when it reports no explicit peak (every
            # CPU build): arguments + outputs + temps.
            record.peak_bytes = arg + out + tmp
            record.peak_bytes_source = "derived"
    if record.peak_bytes is None:
        missing.append("memory_analysis")
    if missing and record.reason is None:
        record.reason = (
            f"runtime reported no {' or '.join(missing)} for this program"
        )
    return record


def _opt_int(value) -> Optional[int]:
    return None if value is None else int(value)


def _first_line(exc: BaseException) -> str:
    return (str(exc).splitlines() or ["<no message>"])[0][:200]


def capture_engine_cost(
    engine: str,
    V: int,
    M: int,
    epochs: int,
    *,
    yuma_version: str = "Yuma 1 (paper)",
    config=None,
    dtype=None,
    save_bonds: bool = False,
    save_incentives: bool = False,
) -> CostRecord:
    """AOT-lower one engine rung at `[epochs, V, M]` from
    ``ShapeDtypeStruct`` specs (nothing is allocated) and capture its
    cost surface. The fused Pallas rungs are captured only on TPU — off
    it they return the explicit-null record with a reason, because the
    interpret-mode emulation's cost surface is not the chip program's.
    """
    import jax
    import jax.numpy as jnp

    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.models.variants import variant_for_version

    if engine not in ENGINE_RUNGS:
        raise ValueError(
            f"unknown engine {engine!r}; expected one of {ENGINE_RUNGS}"
        )
    config = config if config is not None else YumaConfig()
    dtype = jnp.dtype(dtype if dtype is not None else jnp.float32)
    spec = variant_for_version(yuma_version)
    backend = jax.default_backend()
    W = jax.ShapeDtypeStruct((epochs, V, M), dtype)
    S = jax.ShapeDtypeStruct((epochs, V), dtype)
    scalar_i32 = jax.ShapeDtypeStruct((), jnp.int32)

    if engine != "xla":
        if backend != "tpu":
            return CostRecord(
                engine=engine, backend=backend, V=V, M=M, epochs=epochs,
                reason=(
                    "fused Pallas rung lowers to the chip program only on "
                    f"TPU (backend={backend}); interpret-mode cost is not "
                    "comparable"
                ),
            )
        try:
            from yuma_simulation_tpu.simulation.engine import (
                _simulate_case_fused,
            )
            from yuma_simulation_tpu.simulation.planner import rung_flags

            fn = jax.jit(
                functools.partial(
                    _simulate_case_fused,
                    config=config,
                    spec=spec,
                    save_bonds=save_bonds,
                    save_incentives=save_incentives,
                    **rung_flags(engine),
                )
            )
            lowered = fn.lower(W, S, scalar_i32, scalar_i32)
        except Exception as e:
            return CostRecord(
                engine=engine, backend=backend, V=V, M=M, epochs=epochs,
                reason=f"lowering failed: {_first_line(e)}",
            )
        return capture_compiled(
            lowered, engine=engine, V=V, M=M, epochs=epochs
        )

    try:
        from yuma_simulation_tpu.ops.consensus import resolve_consensus_impl
        from yuma_simulation_tpu.simulation.engine import _simulate_scan

        lowered = _simulate_scan.lower(
            W,
            S,
            scalar_i32,
            scalar_i32,
            config,
            spec,
            save_bonds=save_bonds,
            save_incentives=save_incentives,
            save_consensus=False,
            consensus_impl=resolve_consensus_impl("auto", V, M),
        )
    except Exception as e:
        return CostRecord(
            engine=engine, backend=backend, V=V, M=M, epochs=epochs,
            reason=f"lowering failed: {_first_line(e)}",
        )
    return capture_compiled(lowered, engine=engine, V=V, M=M, epochs=epochs)


def capture_engine_costs(
    V: int,
    M: int,
    epochs: int,
    *,
    engines: Sequence[str] = ENGINE_RUNGS,
    yuma_version: str = "Yuma 1 (paper)",
    config=None,
    dtype=None,
    save_bonds: bool = False,
    save_incentives: bool = False,
) -> dict[str, CostRecord]:
    """One :class:`CostRecord` per engine rung (null-with-reason where a
    rung is unavailable) — the cost report's payload."""
    return {
        engine: capture_engine_cost(
            engine, V, M, epochs,
            yuma_version=yuma_version, config=config, dtype=dtype,
            save_bonds=save_bonds, save_incentives=save_incentives,
        )
        for engine in engines
    }


# ---------------------------------------------------------------------------
# Rooflines


@dataclasses.dataclass
class Roofline:
    """A rung's position against the device roofline. ``None`` fields
    mean the spec or the record lacked the inputs (unknown device, null
    cost capture)."""

    engine: str
    device: str
    arithmetic_intensity: Optional[float] = None  # flops / byte
    ridge_intensity: Optional[float] = None  # peak_flops / bandwidth
    bound: Optional[str] = None  # "compute" | "memory"
    predicted_seconds: Optional[float] = None
    predicted_epochs_per_sec: Optional[float] = None
    measured_epochs_per_sec: Optional[float] = None
    #: measured / predicted — the fraction of the roofline actually hit.
    attained_fraction: Optional[float] = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def roofline(
    record: CostRecord,
    spec: Optional[DeviceSpec] = None,
    *,
    measured_epochs_per_sec: Optional[float] = None,
) -> Roofline:
    """Classify `record` compute- vs memory-bound under `spec` and
    predict the epochs/s ceiling: ``t = max(flops/peak_flops,
    bytes/bandwidth)`` (the classic roofline time model), epochs/s =
    epochs / t. With a measured rate, reports the attained fraction of
    the prediction — the number that says whether a BENCH regression is
    a software problem or the hardware wall.

    Honesty note: XLA's ``cost_analysis`` amortizes ``lax.scan``/while
    bodies (the body is counted ONCE regardless of trip count — pinned
    by tests/unit/test_cost.py), so for scan-shaped programs the
    prediction is an OPTIMISTIC ceiling, not a forecast. That is still
    the right tool for both consumers: a ceiling bounds what the rung
    could ever do, and at a fixed shape the numbers are bitwise
    commit-to-commit comparable, which is all perfgate needs."""
    spec = resolve_device_spec(spec)
    out = Roofline(
        engine=record.engine,
        device=spec.name,
        measured_epochs_per_sec=measured_epochs_per_sec,
    )
    if record.flops is not None and record.bytes_accessed:
        out.arithmetic_intensity = record.flops / record.bytes_accessed
    if spec.peak_flops and spec.hbm_bandwidth:
        out.ridge_intensity = spec.peak_flops / spec.hbm_bandwidth
    if out.arithmetic_intensity is not None and out.ridge_intensity is not None:
        out.bound = (
            "compute"
            if out.arithmetic_intensity >= out.ridge_intensity
            else "memory"
        )
    t_compute = (
        record.flops / spec.peak_flops
        if record.flops is not None and spec.peak_flops
        else None
    )
    t_memory = (
        record.bytes_accessed / spec.hbm_bandwidth
        if record.bytes_accessed is not None and spec.hbm_bandwidth
        else None
    )
    candidates = [t for t in (t_compute, t_memory) if t is not None]
    if candidates:
        out.predicted_seconds = max(candidates)
        if out.predicted_seconds > 0 and record.epochs > 0:
            out.predicted_epochs_per_sec = (
                record.epochs / out.predicted_seconds
            )
    if (
        measured_epochs_per_sec is not None
        and out.predicted_epochs_per_sec
    ):
        out.attained_fraction = (
            measured_epochs_per_sec / out.predicted_epochs_per_sec
        )
    return out


# ---------------------------------------------------------------------------
# HBM preflight (analytic — zero compiles, zero allocation)


class HBMPreflightError(ValueError):
    """The predicted peak HBM footprint exceeds the device capacity —
    the dispatch was rejected BEFORE compilation. A ``ValueError``
    deliberately: :func:`..resilience.errors.classify_failure` treats it
    as a caller error, so the engine ladder never burns retries on a
    shape that deterministically cannot fit (re-shape, shard, or stream
    instead — the message says which would fit)."""

    def __init__(
        self,
        message: str,
        verdict: Optional["PreflightVerdict"] = None,
    ):
        super().__init__(message)
        self.verdict = verdict


@dataclasses.dataclass
class FootprintEstimate:
    """Predicted peak resident bytes for one dispatch, per device, with
    the per-term breakdown (bytes)."""

    total_bytes: int
    breakdown: dict
    V: int
    M: int
    resident_epochs: int
    miner_shards: int
    batch_lanes: int

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def estimate_hbm_bytes(
    V: int,
    M: int,
    *,
    resident_epochs: int = 0,
    itemsize: int = 4,
    save_bonds: bool = False,
    save_incentives: bool = False,
    save_consensus: bool = False,
    miner_shards: int = 1,
    batch_lanes: int = 1,
) -> FootprintEstimate:
    """Predict one dispatch's peak resident bytes PER DEVICE from shapes
    alone. `resident_epochs` is the epoch-stack length materialized on
    device (0 for the constant-weights paths, the chunk length under
    streaming, E for monolithic `simulate`); `miner_shards` divides
    every miner-axis buffer (the `[V, M]` working set and the `[*, M]`
    streams), `batch_lanes` multiplies everything (scenario-batched
    dispatches where each device holds `batch_lanes` lanes).

    Deliberately an upper-bound model: the epoch stack + saved output
    streams exactly, plus :data:`WORKING_SET_VM_BUFFERS` `[V, M]`
    buffers for the carry/intermediates/XLA temps. It exists to reject
    what cannot fit, not to certify what barely might.
    """
    ms = max(1, int(miner_shards))
    lanes = max(1, int(batch_lanes))
    m_local = -(-int(M) // ms)  # ceil: the widest shard pays the bill
    vm = int(V) * m_local * itemsize
    breakdown = {
        "weights_stack": resident_epochs * vm,
        "stakes_stack": resident_epochs * int(V) * itemsize,
        "working_set": WORKING_SET_VM_BUFFERS * vm,
        "dividends_out": resident_epochs * int(V) * itemsize,
        "bonds_out": resident_epochs * vm if save_bonds else 0,
        "incentives_out": (
            resident_epochs * m_local * itemsize if save_incentives else 0
        ),
        "consensus_out": (
            resident_epochs * m_local * itemsize if save_consensus else 0
        ),
    }
    breakdown = {k: int(v) * lanes for k, v in breakdown.items()}
    return FootprintEstimate(
        total_bytes=sum(breakdown.values()),
        breakdown=breakdown,
        V=int(V),
        M=int(M),
        resident_epochs=int(resident_epochs),
        miner_shards=ms,
        batch_lanes=lanes,
    )


@dataclasses.dataclass
class PreflightVerdict:
    """One preflight decision. ``fits`` is None when the device capacity
    is unknown (every CPU build without an override) — the preflight
    passes open rather than guessing."""

    label: str
    fits: Optional[bool]
    predicted_bytes: int
    capacity_bytes: Optional[int]
    fraction: float
    device: str
    suggestion: Optional[str] = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def preflight_enabled() -> bool:
    return os.environ.get(PREFLIGHT_ENV, "1").lower() not in (
        "0", "off", "false",
    )


def preflight_hbm(
    label: str,
    estimate: FootprintEstimate,
    *,
    spec: Optional[DeviceSpec] = None,
    fraction: float = DEFAULT_MEMORY_FRACTION,
    raise_on_reject: bool = True,
) -> PreflightVerdict:
    """The advisor check: predicted peak bytes vs usable device memory.

    A shape that fits (or an unknown-capacity device) returns the
    verdict silently. A shape that cannot fit emits exactly one typed
    ``event=preflight_rejected`` record — label, predicted vs capacity,
    shard/stream suggestion — and raises :class:`HBMPreflightError`
    (suppress with ``raise_on_reject=False`` to get the verdict back
    for advisory flows). Disabled globally via ``YUMA_TPU_PREFLIGHT=0``.
    """
    spec = resolve_device_spec(spec)
    if not preflight_enabled() or not spec.memory_bytes:
        return PreflightVerdict(
            label=label,
            fits=None,
            predicted_bytes=estimate.total_bytes,
            capacity_bytes=spec.memory_bytes,
            fraction=fraction,
            device=spec.name,
        )
    budget = int(spec.memory_bytes * fraction)
    if estimate.total_bytes <= budget:
        return PreflightVerdict(
            label=label,
            fits=True,
            predicted_bytes=estimate.total_bytes,
            capacity_bytes=spec.memory_bytes,
            fraction=fraction,
            device=spec.name,
        )
    verdict = PreflightVerdict(
        label=label,
        fits=False,
        predicted_bytes=estimate.total_bytes,
        capacity_bytes=spec.memory_bytes,
        fraction=fraction,
        device=spec.name,
        suggestion=_suggest(estimate, budget),
    )
    from yuma_simulation_tpu.utils.logging import log_event

    log_event(
        logger,
        "preflight_rejected",
        label=label,
        V=estimate.V,
        M=estimate.M,
        resident_epochs=estimate.resident_epochs,
        miner_shards=estimate.miner_shards,
        batch_lanes=estimate.batch_lanes,
        predicted_gib=round(estimate.total_bytes / 2**30, 2),
        capacity_gib=round(spec.memory_bytes / 2**30, 2),
        device=spec.name,
        suggestion=verdict.suggestion or "",
    )
    if raise_on_reject:
        raise HBMPreflightError(
            f"{label}: predicted peak HBM "
            f"{estimate.total_bytes / 2**30:.2f} GiB exceeds "
            f"{fraction:.0%} of {spec.name} capacity "
            f"({spec.memory_bytes / 2**30:.2f} GiB) for shape "
            f"V={estimate.V} M={estimate.M} "
            f"resident_epochs={estimate.resident_epochs}. "
            f"{verdict.suggestion or ''}".rstrip(),
            verdict,
        )
    return verdict


def _suggest(estimate: FootprintEstimate, budget: int) -> Optional[str]:
    """An actionable way out: the max_resident_epochs chunk length that
    would fit (when the epoch stack dominates), else the miner-shard
    count that would (when the working set does)."""
    per_epoch = sum(
        v // max(1, estimate.resident_epochs)
        for k, v in estimate.breakdown.items()
        if k.endswith("_stack") or k.endswith("_out")
    ) if estimate.resident_epochs else 0
    fixed = estimate.breakdown["working_set"]
    if per_epoch and fixed < budget:
        chunk = (budget - fixed) // per_epoch
        if chunk >= 1:
            return (
                f"stream with max_resident_epochs<={chunk} or shard the "
                "miner axis"
            )
    if fixed > budget:
        shards = -(-fixed * estimate.miner_shards // budget)
        return (
            f"shard the miner axis over >= {shards} devices (or reduce "
            "V x M)"
        )
    return "shard the miner axis or reduce the resident epoch stack"
