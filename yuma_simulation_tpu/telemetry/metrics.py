"""Process-local metrics registry: counters, gauges, histograms.

The BENCH trajectory and any serious perf work need machine-readable
rate/compile/memory counters attached to every run — not log prose. This
is the minimal, dependency-free substrate:

- :class:`Counter` / :class:`Gauge` / :class:`Histogram` with
  thread-safe mutation (the watchdog/supervisor callbacks increment from
  worker threads);
- :class:`MetricsRegistry` — get-or-create by name, a point-in-time
  :meth:`~MetricsRegistry.snapshot`, a crash-safe JSONL snapshot sink
  (:meth:`~MetricsRegistry.publish_snapshot`, atomic via
  :func:`..utils.checkpoint.publish_atomic`), and Prometheus text
  exposition (:meth:`~MetricsRegistry.prometheus_text`) for scraping;
- the process singleton via :func:`get_registry` — what the resilience
  tier feeds without any plumbing.

The well-known-series catalog LIVES in :mod:`.registry` (every
counter/gauge/histogram name, with kind and consumers), not here: the
hand-maintained table this docstring used to carry had silently drifted
nine live series behind reality (the drift counters, the serve canary
counters, the SLO burn gauges, ``device_bytes_in_use``) by PR 11, which
is exactly the rot a prose table invites. ``tools/jaxlint``'s JX202
now fails any ``counter()``/``gauge()``/``histogram()`` call whose name
the registry does not declare, so the catalog cannot drift again.
Series are incremented at their SOURCE, exactly once; serving-tier
series are registered eagerly at service construction so ``/metrics``
and flight-bundle snapshots expose them even at zero.

Host-side ONLY: nothing here may be called from inside traced code (the
zero-warm-repeat compile budgets of tests/unit/test_recompilation.py and
jaxlint's impurity rules stay authoritative) — every producer above sits
on the host side of a dispatch.
"""

from __future__ import annotations

import json
import logging
import math
import pathlib
import re
import threading
import time
from typing import Optional, Sequence, Union

logger = logging.getLogger(__name__)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

#: Default histogram boundaries: wall-clock seconds from 1 ms to ~15 min,
#: the span of a unit dispatch (compile included) on any supported
#: backend.
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.025, 0.1, 0.5, 2.0, 10.0, 60.0, 300.0, 900.0,
)


_SEQ_LOCK = threading.Lock()
_SEQ = 0


def _next_seq() -> int:
    """Monotone per-process sequence number stamped into every snapshot
    record (additive, 0.24.0): cumulative snapshots carry no ordering of
    their own once bundles from several processes/segments merge, and
    wall clocks can collide or step backwards across hosts. ``(source,
    seq)`` gives the time-series store (:mod:`.timeseries`) an exact
    dedupe identity so merges are order-independent."""
    global _SEQ
    with _SEQ_LOCK:
        _SEQ += 1
        return _SEQ


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} is not Prometheus-compatible "
            "([a-zA-Z_:][a-zA-Z0-9_:]*)"
        )
    return name


class Counter:
    """Monotonic counter. `inc` is thread-safe; negative increments are
    rejected (a counter that can go down is a gauge)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = _check_name(name)
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: Union[int, float] = 1) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value; `set`/`inc` thread-safe."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = _check_name(name)
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: Union[int, float]) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: Union[int, float] = 1) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-boundary histogram with Prometheus cumulative-bucket
    semantics (`le` upper bounds, implicit ``+Inf``)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        self.name = _check_name(name)
        self.help = help
        # Finite bounds only: the cumulative +Inf bucket is ALWAYS
        # emitted from the total count (exposition conformance), so a
        # caller-passed inf/nan bound would only shadow it with a
        # malformed `le` label.
        bounds = tuple(
            sorted(
                {float(b) for b in buckets if math.isfinite(float(b))}
            )
        )
        if not bounds:
            raise ValueError(
                "histogram needs at least one finite bucket bound"
            )
        self.bounds = bounds
        self._lock = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value: Union[int, float]) -> None:
        v = float(value)
        idx = len(self.bounds)
        for i, b in enumerate(self.bounds):
            if v <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        """`{"count", "sum", "buckets": {le_str: cumulative_count}}`."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cumulative: dict[str, int] = {}
        acc = 0
        for b, c in zip(self.bounds, counts):
            acc += c
            cumulative[repr(b)] = acc
        cumulative["+Inf"] = total
        return {"count": total, "sum": s, "buckets": cumulative}


class MetricsRegistry:
    """Name -> metric, get-or-create, with snapshot/exposition sinks.

    Not a singleton by construction — tests build throwaway registries —
    but production code shares the process registry via
    :func:`get_registry`.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Union[Counter, Gauge, Histogram]] = {}

    # -- get-or-create --------------------------------------------------

    def _get_or_create(self, cls, name: str, **kwargs):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = cls(name, **kwargs)
                self._metrics[name] = metric
                return metric
        if not isinstance(metric, cls):
            raise TypeError(
                f"metric {name!r} already registered as {metric.kind}, "
                f"not {cls.kind}"
            )
        return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help=help)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help=help, buckets=buckets)

    def reset(self) -> None:
        """Drop every registered metric (tests; never production)."""
        with self._lock:
            self._metrics.clear()

    # -- sinks -----------------------------------------------------------

    def snapshot(self) -> dict:
        """Point-in-time `{"counters": {...}, "gauges": {...},
        "histograms": {...}}` of every registered series."""
        with self._lock:
            metrics = dict(self._metrics)
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(metrics.items()):
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            else:
                out["histograms"][name] = m.snapshot()
        return out

    def publish_snapshot(
        self, path: Union[str, pathlib.Path], **meta
    ) -> dict:
        """Append one snapshot line to the JSONL sink at `path` under the
        crash-safety contract (whole-file atomic republish via
        :func:`..utils.checkpoint.publish_atomic` — the ledger's
        pattern): at every instant the sink is a complete parseable
        prefix. Undecodable lines from a pre-atomic writer are dropped
        with a warning (the shared
        :func:`..utils.checkpoint.read_jsonl_tolerant` reader). `meta`
        (e.g. ``run_id=...``) rides the line. Returns the appended
        record."""
        from yuma_simulation_tpu.utils.checkpoint import (
            publish_atomic,
            read_jsonl_tolerant,
        )

        path = pathlib.Path(path)
        record = {
            "t": round(time.time(), 6),
            "seq": _next_seq(),
            **meta,
            **self.snapshot(),
        }
        records = read_jsonl_tolerant(path)
        records.append(record)
        payload = "".join(
            json.dumps(r, sort_keys=True) + "\n" for r in records
        )
        publish_atomic(path, payload.encode())
        return record

    def append_snapshot(
        self, path: Union[str, pathlib.Path], **meta
    ) -> dict:
        """Append one snapshot line to the JSONL sink at `path` WITHOUT
        re-reading/republishing the whole file — O(one line) however
        large the sink has grown, via
        :func:`..utils.checkpoint.append_durable`. The continuous-
        telemetry twin of :meth:`publish_snapshot` for rotation-mode
        flight segments: a crash can tear only the appended TAIL line
        (readers are torn-tail tolerant), and snapshots are cumulative
        so a lost tail costs one sample, not history. `meta` rides the
        line; returns the appended record."""
        from yuma_simulation_tpu.utils.checkpoint import append_durable

        record = {
            "t": round(time.time(), 6),
            "seq": _next_seq(),
            **meta,
            **self.snapshot(),
        }
        append_durable(
            pathlib.Path(path),
            (json.dumps(record, sort_keys=True) + "\n").encode(),
        )
        return record

    def prometheus_text(self) -> str:
        """The registry in Prometheus text exposition format (0.0.4) —
        serve or dump this for scraping; no client library needed.

        Conformance contract (pinned by the exposition test in
        tests/unit/test_telemetry.py): every histogram emits its
        buckets in ascending ``le`` order ending with a cumulative
        ``+Inf`` bucket equal to ``_count``; bucket counts are monotone
        non-decreasing (cumulative by construction, the ``+Inf`` total
        included); HELP text is escaped per the format (backslash and
        newline)."""
        with self._lock:
            metrics = dict(self._metrics)
        out: list[str] = []
        for name, m in sorted(metrics.items()):
            if m.help:
                out.append(f"# HELP {name} {_escape_help(m.help)}")
            out.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, (Counter, Gauge)):
                out.append(f"{name} {_fmt_value(m.value)}")
            else:
                snap = m.snapshot()
                # snapshot() yields bounds in ascending order with the
                # "+Inf" total last; emit in exactly that order.
                for le, c in snap["buckets"].items():
                    out.append(f'{name}_bucket{{le="{le}"}} {c}')
                out.append(f"{name}_sum {_fmt_value(snap['sum'])}")
                out.append(f"{name}_count {snap['count']}")
        return "\n".join(out) + ("\n" if out else "")


def _fmt_value(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(v)


def _escape_help(text: str) -> str:
    """HELP-line escaping per the exposition format: backslash first,
    then newline — a help string must never break line framing."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-local registry every production producer feeds."""
    return _REGISTRY


def record_epoch_rate(
    label: str,
    *,
    epochs: Optional[int] = None,
    seconds: Optional[float] = None,
    epochs_per_sec: Optional[float] = None,
    cv: Optional[float] = None,
    registry: Optional[MetricsRegistry] = None,
    logger_: Optional[logging.Logger] = None,
) -> Optional[float]:
    """The one epoch-rate reporting path (`simulate`, `bench.py`, the
    supervisor): feeds ``epochs_total``/``epochs_per_sec`` in the
    registry and emits exactly one ``event=epoch_rate`` record. Pass
    either a precomputed `epochs_per_sec` or `epochs` + `seconds`.
    `cv` (timing dispersion across repeats, from
    :func:`..utils.timing.time_best`) rides the record and the
    ``epochs_per_sec_cv`` gauge so downstream regression gates
    (`tools/perfgate.py`) can widen tolerance on noisy measurements.
    Returns the rate (None when it cannot be derived)."""
    from yuma_simulation_tpu.utils.logging import log_event

    reg = registry if registry is not None else get_registry()
    if epochs_per_sec is None and epochs is not None and seconds:
        epochs_per_sec = epochs / seconds
    if epochs:
        reg.counter(
            "epochs_total", help="simulated epochs (lanes x E)"
        ).inc(epochs)
    if epochs_per_sec is not None:
        reg.gauge(
            "epochs_per_sec", help="last observed simulated epochs/sec"
        ).set(epochs_per_sec)
    if cv is not None:
        reg.gauge(
            "epochs_per_sec_cv",
            help="timing dispersion (CV across repeats) of the last rate",
        ).set(cv)
    log_event(
        logger_ if logger_ is not None else logger,
        "epoch_rate",
        level=logging.INFO,
        label=label,
        epochs="" if epochs is None else epochs,
        seconds="" if seconds is None else f"{seconds:.3f}",
        epochs_per_sec=(
            "" if epochs_per_sec is None else f"{epochs_per_sec:.1f}"
        ),
        cv="" if cv is None else f"{cv:.4f}",
    )
    return epochs_per_sec
