"""Generate the per-beta total-dividends CSV sheets.

Equivalent of the reference's `scripts/total_dividends_sheet_generator.py`
(reference total_dividends_sheet_generator.py:12-66): same file naming
(`total_dividends_b{beta}.csv`), same `%.6f` formatting, same NaN check —
with a CLI for the sweep values and output dir, and each version's 14-case
suite simulated as one batched XLA computation instead of 14 Python loops.
"""

from __future__ import annotations

import argparse
import json
import pathlib

from yuma_simulation_tpu.models.config import SimulationHyperparameters
from yuma_simulation_tpu.models.variants import canonical_versions
from yuma_simulation_tpu.reporting.tables import generate_total_dividends_table
from yuma_simulation_tpu.scenarios import get_cases
from yuma_simulation_tpu.telemetry import RunContext, span
from yuma_simulation_tpu.utils import profile_trace, setup_logging


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--bond-penalty",
        nargs="+",
        default=["0", "0.5", "0.99", "1.0"],
        help="bond_penalty sweep values; kept as strings so output file "
        "names match the reference's (b0, b0.5, b0.99, b1.0)",
    )
    parser.add_argument(
        "--out-dir", type=pathlib.Path, default=pathlib.Path(".")
    )
    parser.add_argument(
        "--profile-dir",
        default=None,
        help="write a jax.profiler trace (Perfetto/XPlane) of the whole "
        "build under this directory (default: no profiling)",
    )
    parser.add_argument(
        "--executable-cache",
        default=None,
        metavar="DIR",
        help="AOT executable-cache directory (README 'Cold start'): a "
        "second invocation against the same directory loads published "
        "executables instead of re-paying every XLA compile; the "
        "persistent JAX compilation cache is enabled beside it, and "
        "cache_stats.json is published there on exit",
    )
    parser.add_argument(
        "--fleet-store",
        default=None,
        help="coordinate the per-beta sheets through a shared fleet "
        "store (README 'Fleet sweeps'): N concurrent invocations "
        "pointed at this directory split the beta sweep via "
        "lease-claimed units — each sheet builds exactly once across "
        "the fleet, a dying builder's beta is requeued via lease "
        "expiry, and every invocation writes the complete CSV set",
    )
    args = parser.parse_args(argv)

    # Operator-facing stream (structured event= records included) — the
    # logging setup was previously never wired into any entry point.
    setup_logging()

    cache = None
    if args.executable_cache:
        from yuma_simulation_tpu.simulation.aot import (
            configure_executable_cache,
        )

        cache = configure_executable_cache(args.executable_cache)

    cases = get_cases()
    args.out_dir.mkdir(parents=True, exist_ok=True)

    def build_sheet(bond_penalty: str) -> bytes:
        print(
            f"Generating total dividends sheet for "
            f"bond_penalty={bond_penalty}"
        )
        hp = SimulationHyperparameters(bond_penalty=float(bond_penalty))
        with span(f"sheet:b{bond_penalty}"):
            df = generate_total_dividends_table(
                cases, canonical_versions(), hp
            )
        if df.isnull().values.any():
            print("Warning: NaN values detected in the dividends table.")
        return df.to_csv(index=False, float_format="%.6f").encode()

    def write_sheet(bond_penalty: str, data: bytes) -> None:
        file_name = args.out_dir / f"total_dividends_b{bond_penalty}.csv"
        file_name.write_bytes(data)
        print(f"CSV saved to {file_name}")

    # One telemetry run for the invocation, one span per beta sheet.
    with RunContext(), profile_trace(args.profile_dir):
        if args.fleet_store is not None:
            # The fleet path necessarily writes after completion: the
            # full set only exists once every host's units published.
            from yuma_simulation_tpu.fabric import run_fleet_artifacts

            sheets = run_fleet_artifacts(
                args.bond_penalty,
                build_sheet,
                args.fleet_store,
                tag="dividend_sheets",
                config_fingerprint={
                    "driver": "yuma-dividends",
                    "betas": list(args.bond_penalty),
                },
            )
            for bond_penalty, data in sheets.items():
                write_sheet(bond_penalty, data)
        else:
            # Write each sheet as it completes: a crash mid-sweep keeps
            # every finished CSV, and only one sheet is ever resident.
            for bond_penalty in args.bond_penalty:
                write_sheet(bond_penalty, build_sheet(bond_penalty))
    if cache is not None:
        # Cold-start accounting: this run's hit/miss/build tallies land
        # beside the artifacts (the CI cold-start lane asserts run 2
        # shows zero builds and >= 1 hit).
        print(json.dumps({"executable_cache": cache.write_stats()}))


if __name__ == "__main__":
    main()
