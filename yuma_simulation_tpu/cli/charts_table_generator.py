"""Generate the per-beta chart-table HTML files.

Equivalent of the reference's `scripts/charts_table_generator.py` (which
hard-codes its parameters, reference charts_table_generator.py:12-48) with
a thin CLI on top: sweep values, output dir, case subset and draggable
mode are flags.

Writes `simulation_results_b{beta}.html` per bond_penalty value.
"""

from __future__ import annotations

import argparse
import pathlib

from yuma_simulation_tpu.models.config import SimulationHyperparameters
from yuma_simulation_tpu.models.variants import canonical_versions
from yuma_simulation_tpu.scenarios import create_case, get_cases
from yuma_simulation_tpu.telemetry import RunContext
from yuma_simulation_tpu.utils import profile_trace, setup_logging
from yuma_simulation_tpu.v1.api import generate_chart_table


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--bond-penalty",
        nargs="+",
        default=["0", "0.5", "0.99", "1.0"],
        help="bond_penalty sweep values; kept as strings so output file "
        "names match the reference's (b0, b0.5, b0.99, b1.0)",
    )
    parser.add_argument(
        "--cases",
        nargs="*",
        default=None,
        help="registry keys of cases to include, e.g. 'Case 3' "
        "(default: all registered cases)",
    )
    parser.add_argument(
        "--out-dir", type=pathlib.Path, default=pathlib.Path(".")
    )
    parser.add_argument(
        "--no-draggable",
        action="store_true",
        help="emit the notebook-style table instead of the drag-to-scroll one",
    )
    parser.add_argument(
        "--profile-dir",
        default=None,
        help="write a jax.profiler trace (Perfetto/XPlane) of the whole "
        "build under this directory (default: no profiling)",
    )
    parser.add_argument(
        "--executable-cache",
        default=None,
        metavar="DIR",
        help="AOT executable-cache directory (README 'Cold start'): a "
        "second invocation against the same directory loads published "
        "executables instead of re-paying every XLA compile; the "
        "persistent JAX compilation cache is enabled beside it, and "
        "cache_stats.json is published there on exit",
    )
    parser.add_argument(
        "--fleet-store",
        default=None,
        help="coordinate the per-beta tables through a shared fleet "
        "store (README 'Fleet sweeps'): N concurrent invocations "
        "pointed at this directory split the beta sweep via "
        "lease-claimed units — each table builds exactly once across "
        "the fleet, a dying builder's beta is requeued via lease "
        "expiry, and every invocation writes the complete HTML set",
    )
    args = parser.parse_args(argv)

    # Operator-facing stream (structured event= records included) — the
    # logging setup was previously never wired into any entry point.
    setup_logging()

    cache = None
    if args.executable_cache:
        from yuma_simulation_tpu.simulation.aot import (
            configure_executable_cache,
        )

        cache = configure_executable_cache(args.executable_cache)

    if args.cases:
        cases = [create_case(name) for name in args.cases]
    else:
        cases = get_cases()

    args.out_dir.mkdir(parents=True, exist_ok=True)

    def build_table(bond_penalty: str) -> bytes:
        hp = SimulationHyperparameters(bond_penalty=float(bond_penalty))
        table = generate_chart_table(
            cases,
            canonical_versions(),
            hp,
            draggable_table=not args.no_draggable,
        )
        return table.data.encode("utf-8")

    def write_table(bond_penalty: str, data: bytes) -> None:
        file_name = (
            args.out_dir / f"simulation_results_b{bond_penalty}.html"
        )
        file_name.write_bytes(data)
        print(f"HTML saved to {file_name}")

    # One telemetry run for the whole invocation: every structured
    # record emitted below carries this run_id, and the per-beta suite
    # builds become spans under it (yuma_simulation_tpu.telemetry).
    with RunContext(), profile_trace(args.profile_dir):
        if args.fleet_store is not None:
            # The fleet path necessarily writes after completion: the
            # full set only exists once every host's units published.
            from yuma_simulation_tpu.fabric import run_fleet_artifacts

            tables = run_fleet_artifacts(
                args.bond_penalty,
                build_table,
                args.fleet_store,
                tag="chart_tables",
                config_fingerprint={
                    "driver": "yuma-charts",
                    "betas": list(args.bond_penalty),
                    "cases": [case.name for case in cases],
                    "draggable": not args.no_draggable,
                },
            )
            for bond_penalty, data in tables.items():
                write_table(bond_penalty, data)
        else:
            # Write each table as it completes: a crash mid-sweep keeps
            # every finished HTML, and only one table is ever resident.
            for bond_penalty in args.bond_penalty:
                write_table(bond_penalty, build_table(bond_penalty))
    if cache is not None:
        # Cold-start accounting: this run's hit/miss/build tallies land
        # beside the artifacts (the CI cold-start lane asserts run 2
        # shows zero builds and >= 1 hit).
        import json

        print(json.dumps({"executable_cache": cache.write_stats()}))


if __name__ == "__main__":
    main()
