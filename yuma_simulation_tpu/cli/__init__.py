"""Command-line entry points (installed as yuma-charts / yuma-dividends,
mirrored at the repo's `scripts/` directory for reference-layout parity)."""
