"""Lease-based work-unit claiming over a shared filesystem store.

The fleet scheduler (:mod:`.scheduler`) coordinates hosts through files
only — no coordinator process, no RPC: any filesystem every host can
see (NFS/GCS-fuse on a pod, a plain tmpdir under multiprocess CI) is
the whole control plane. The primitives:

- **Claim** — one lease file per work unit (``leases/unit_NNNNN.lease``).
  Claiming hard-links a fully-written, fsync'd temp file onto the lease
  name: `os.link` fails with ``EEXIST`` if any other host holds the
  name, so exactly one host wins and a reader never observes a partial
  claim (the link publishes complete bytes atomically — the same
  all-or-nothing contract as :func:`..utils.checkpoint.publish_atomic`,
  which the store uses for every other sidecar).
- **Heartbeat** — the holder renews by bumping the lease file's mtime
  (`os.utime`) after verifying it still owns the file (inode identity).
  Liveness is therefore a property of the FILE, not of any connection:
  a SIGKILLed host simply stops renewing.
- **Expiry & steal** — a lease whose mtime is older than the TTL (or
  whose content is torn/unparseable — shared-store corruption must not
  gate work forever) is *stealable*. The stealer atomically renames the
  dead claim to a tombstone (``stale_unit_NNNNN.<nonce>``): rename is
  atomic and the name exists once, so exactly one stealer retires it;
  the loser sees ``ENOENT`` and backs off. The tombstones double as the
  unit's durable steal history — the claim *generation* is their count.
- **Abandon** — a holder whose renewal finds a different inode (or no
  file) under its lease name raises the typed
  :class:`..resilience.errors.LeaseExpired`; the unit now belongs to a
  stealer and the polite (and pointless-to-race, results being
  content-addressed and deterministic) move is to walk away without
  publishing.

Clock note: expiry compares the reader's `time.time()` against the
lease's mtime as stamped by the writer's kernel. On one machine (the
CI drills) these are the same clock; on a real shared store, keep the
TTL an order of magnitude above plausible host clock skew.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import pathlib
import time
import uuid
from typing import Callable, Optional

from yuma_simulation_tpu.resilience.errors import LeaseExpired
from yuma_simulation_tpu.utils.checkpoint import _fsync_dir, _fsync_write
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)

#: Default lease TTL: long enough that a healthy host's heartbeat (TTL/3)
#: never lapses under GC pauses or a slow shared store, short enough
#: that a dead host's units requeue within one unit's compute time.
DEFAULT_TTL_SECONDS = 15.0


@dataclasses.dataclass(frozen=True)
class LeaseInfo:
    """One observed lease file (a scan-time snapshot, not a handle)."""

    unit: int
    host: str
    mtime: float
    #: content was unparseable (truncated/corrupt claim record).
    torn: bool
    #: the observed file's inode — the claim's identity. A steal only
    #: retires the claim it OBSERVED expired (re-checked immediately
    #: before the tombstone rename), so a stale scan snapshot cannot
    #: tombstone a rival stealer's fresh claim.
    inode: int = 0


@dataclasses.dataclass(frozen=True)
class ClaimedLease:
    """A lease THIS store instance holds: the identity the renewal and
    release paths verify (`inode`), plus the claim's steal generation
    (0 = first claim of the unit) and, for stolen units, the host whose
    expired/torn claim was retired."""

    unit: int
    inode: int
    generation: int
    stolen_from: str = ""


class LeaseStore:
    """Per-host view of the shared lease directory. One instance per
    (host, fleet run); holds the inode identities of its own claims.

    `_pause` is a test-only interleaving hook: called with a stage name
    (``"read"``, ``"steal"``, ``"link"``) between the protocol's atomic
    steps so the race-property tests can schedule adversarial
    interleavings deterministically. A no-op in production.
    """

    def __init__(
        self,
        directory: str | pathlib.Path,
        host_id: str,
        *,
        ttl_seconds: float = DEFAULT_TTL_SECONDS,
    ):
        if ttl_seconds <= 0:
            raise ValueError("ttl_seconds must be > 0")
        self.directory = pathlib.Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.host_id = host_id
        self.ttl_seconds = float(ttl_seconds)
        self._held: dict[int, ClaimedLease] = {}
        self._pause: Callable[[str], None] = lambda stage: None

    # -- paths ----------------------------------------------------------

    def lease_path(self, unit: int) -> pathlib.Path:
        return self.directory / f"unit_{unit:05d}.lease"

    def _tombstones(self, unit: int) -> list[pathlib.Path]:
        return sorted(self.directory.glob(f"stale_unit_{unit:05d}.*"))

    def generation(self, unit: int) -> int:
        """The unit's steal generation so far (= tombstone count): 0
        means the unit has never been stolen."""
        return len(self._tombstones(unit))

    # -- observation ----------------------------------------------------

    def read(self, unit: int) -> Optional[LeaseInfo]:
        """The unit's current lease as observed on disk, or None when
        unclaimed. A torn claim record (truncated JSON — shared-store
        corruption, or a `LeaseTearFault` drill) loads as
        ``torn=True`` rather than raising: scanners must treat it as
        stealable, never as a crash."""
        path = self.lease_path(unit)
        try:
            st = os.stat(path)
        except FileNotFoundError:
            return None
        host, torn = "", True
        try:
            data = json.loads(path.read_text())
            if isinstance(data, dict):
                host, torn = str(data.get("host", "")), False
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, OSError, UnicodeDecodeError):
            pass
        return LeaseInfo(
            unit=unit,
            host=host,
            mtime=st.st_mtime,
            torn=torn,
            inode=st.st_ino,
        )

    def is_stealable(self, info: LeaseInfo, now: Optional[float] = None) -> bool:
        """Whether `info`'s claim no longer protects its unit: the
        holder stopped heartbeating past the TTL, or the claim record
        itself is torn (an unparseable claim cannot be trusted to gate
        work, whatever its mtime says)."""
        if info.torn:
            return True
        now = time.time() if now is None else now
        return (now - info.mtime) > self.ttl_seconds

    # -- the claim protocol ---------------------------------------------

    def try_claim(self, unit: int) -> Optional[ClaimedLease]:
        """Attempt to claim `unit`. Returns the held lease, or None when
        another host holds a live claim (or won the race). Expired/torn
        claims are stolen: retired to a tombstone first (atomic rename —
        exactly one stealer succeeds), then claimed fresh."""
        path = self.lease_path(unit)
        self._pause("read")
        info = self.read(unit)
        stolen_from = ""
        if info is not None:
            if not self.is_stealable(info):
                return None
            tomb = self.directory / (
                f"stale_unit_{unit:05d}.{uuid.uuid4().hex[:8]}"
            )
            self._pause("steal")
            try:
                # Retire only the claim we OBSERVED expired: if the
                # inode under the lease name changed since our read, a
                # rival stealer already retired it and claimed fresh —
                # renaming now would tombstone a LIVE claim.
                if os.stat(path).st_ino != info.inode:
                    return None
                os.rename(path, tomb)
            except FileNotFoundError:
                # Another stealer retired this claim first; its fresh
                # lease is (or is about to be) live — back off.
                return None
            _fsync_dir(self.directory)
            stolen_from = info.host
            log_event(
                logger,
                "lease_stolen",
                unit=unit,
                prior_host=stolen_from or ("<torn>" if info.torn else "?"),
                torn=info.torn,
                by=self.host_id,
            )
        record = {
            "unit": unit,
            "host": self.host_id,
            "claimed_at": round(time.time(), 6),
        }
        # The claim carries the claimer's trace identity (additive —
        # old readers only look at "host"): a lease on disk names not
        # just WHO holds the unit but which distributed trace the work
        # lands in, so a stuck claim is greppable back to its sweep.
        try:
            from yuma_simulation_tpu.telemetry.propagation import (
                current_trace_context,
            )

            ctx = current_trace_context()
            if ctx is not None:
                record["trace"] = ctx.to_traceparent()
        except Exception:
            pass  # propagation must never break claiming
        payload = json.dumps(record, sort_keys=True).encode()
        tmp = self.directory / (
            f".claim.{self.host_id}.{uuid.uuid4().hex[:8]}.tmp"
        )
        _fsync_write(tmp, lambda f: f.write(payload))
        self._pause("link")
        try:
            os.link(tmp, path)
            inode = os.stat(tmp).st_ino
        except FileExistsError:
            return None
        finally:
            tmp.unlink(missing_ok=True)
        _fsync_dir(self.directory)
        # Generation is counted AFTER the link: any tombstone that
        # exists by now was retired before our claim could succeed, so
        # the count is exact even when a rival stealer did the retiring.
        claim = ClaimedLease(
            unit=unit,
            inode=inode,
            generation=self.generation(unit),
            stolen_from=stolen_from,
        )
        self._held[unit] = claim
        return claim

    def renew(self, unit: int) -> None:
        """Heartbeat: refresh the held lease's mtime. Raises the typed
        :class:`LeaseExpired` when the lease name no longer carries OUR
        claim (stolen after expiry or tear) — the holder must abandon
        the unit without publishing."""
        held = self._held.get(unit)
        if held is None:
            raise LeaseExpired(
                f"host {self.host_id} holds no lease for unit {unit}",
                unit=unit,
            )
        path = self.lease_path(unit)
        try:
            st = os.stat(path)
            if st.st_ino != held.inode:
                raise FileNotFoundError
            os.utime(path)
        except FileNotFoundError:
            self._held.pop(unit, None)
            usurper = self.read(unit)
            raise LeaseExpired(
                f"unit {unit} lease lost by {self.host_id} (stolen by "
                f"{usurper.host if usurper else '<nobody yet>'})",
                unit=unit,
                holder=usurper.host if usurper else None,
            ) from None
        # Deterministic drill hook: tear our OWN live lease after N
        # renewals (shared-store corruption simulation).
        from yuma_simulation_tpu.resilience import faults

        faults.maybe_tear_lease(path, unit)

    def still_owner(self, unit: int) -> bool:
        """Whether this host still holds `unit`'s lease (a renew that
        swallows the typed failure — the pre-publish ownership check)."""
        try:
            self.renew(unit)
        except LeaseExpired:
            return False
        return True

    def release(self, unit: int) -> None:
        """Drop the held lease after its result is published. Only
        removes the file while it still carries OUR claim (inode
        check); a stolen lease belongs to the stealer and stays. The
        annotation sidecar (if any) goes with it — an advertisement
        must never outlive the claim it describes."""
        held = self._held.pop(unit, None)
        if held is None:
            return
        path = self.lease_path(unit)
        try:
            if os.stat(path).st_ino == held.inode:
                path.unlink(missing_ok=True)
                self.annotation_path(unit).unlink(missing_ok=True)
        except FileNotFoundError:
            pass

    # -- heartbeat annotations -------------------------------------------

    def annotation_path(self, unit: int) -> pathlib.Path:
        return self.directory / f"unit_{unit:05d}.ad.json"

    def annotate(self, unit: int, payload: dict) -> None:
        """Publish a heartbeat ADVERTISEMENT beside the held lease: an
        arbitrary JSON payload (atomic tmp+rename, torn-read safe) a
        scanner can pair with the lease's liveness. The serve scale-out
        tier rides this — each worker advertises its held StateCache
        prefixes and warm shape buckets here, and the router scores
        claims against the ad ONLY while :meth:`read` +
        :meth:`is_stealable` say the slot lease is live (a dead
        worker's stale ad never wins a claim). Raises the typed
        :class:`LeaseExpired` when the lease is no longer ours:
        advertising for a stolen slot would point the router at a
        usurped identity."""
        held = self._held.get(unit)
        if held is None:
            raise LeaseExpired(
                f"host {self.host_id} holds no lease for unit {unit}",
                unit=unit,
            )
        record = dict(payload)
        record.setdefault("host", self.host_id)
        record.setdefault("unit", unit)
        tmp = self.directory / (
            f".ad.{self.host_id}.{uuid.uuid4().hex[:8]}.tmp"
        )
        data = json.dumps(record, sort_keys=True).encode()
        _fsync_write(tmp, lambda f: f.write(data))
        os.replace(tmp, self.annotation_path(unit))

    def read_annotation(self, unit: int) -> Optional[dict]:
        """The unit's last advertisement, or None when absent/torn (a
        torn ad reads as None, never a crash — exactly like a torn
        lease record, shared-store writes can always be caught
        mid-rename)."""
        try:
            data = json.loads(self.annotation_path(unit).read_text())
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, OSError, UnicodeDecodeError):
            return None
        return data if isinstance(data, dict) else None
