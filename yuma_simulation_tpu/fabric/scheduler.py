"""The work-stealing fleet scheduler: hosts, heartbeats, unit dispatch.

One :class:`FleetHost` per process. Each host loops over the store's
pending units, claims one at a time through the lease protocol
(:mod:`.lease`), computes it through its LOCAL
:class:`..resilience.supervisor.SweepSupervisor` (so every unit inherits
the full single-host resilience stack — deadline watchdog, engine
ladder, NaN quarantine, elastic mesh), and publishes the result
content-addressed into the shared store (:mod:`.store`). A heartbeat
thread renews the claim while the unit computes; when a host dies, its
lease stops renewing, expires, and any surviving host STEALS the unit
and re-executes it — re-execution is always safe (units are pure) and
the at-most-once publish gate keeps the store single-valued.

Telemetry: every fabric event rides the host's fleet-scoped span chain
``host -> fleetunit -> (the supervisor's unit/attempt/engine-rung
spans)`` and lands in the host's crash-safe ledger under
``hosts/<host_id>/`` — so ``tools/obsreport.py`` renders a per-host
fleet timeline and :func:`..fabric.health.build_fleet_report`
cross-checks against the merged ledgers.

Bitwise contract (the PR 3 drill guarantee, fleet-wide): unit lane
bounds come from the manifest, each unit dispatches through the same
deterministic `DispatchPlan` machinery regardless of WHICH host runs
it, and healthy lanes of a faulted fleet run are bitwise-identical to
an unfaulted run's.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import pathlib
import socket
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from yuma_simulation_tpu.fabric.lease import (
    DEFAULT_TTL_SECONDS,
    ClaimedLease,
    LeaseStore,
)
from yuma_simulation_tpu.fabric.store import FleetStore
from yuma_simulation_tpu.resilience.errors import LeaseExpired
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)


def default_host_id() -> str:
    """Process-unique, operator-greppable host identity."""
    return f"host-{socket.gethostname()}-{os.getpid()}"


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Knobs for one host's participation in a fleet sweep.

    `directory` is the shared store; `lease_ttl_seconds` bounds how long
    a dead host's units stay locked (heartbeats renew at TTL/3 by
    default); `poll_seconds` is the idle re-scan interval while other
    hosts hold the remaining work; `max_wait_seconds` bounds the whole
    participation so a wedged store fails loudly instead of spinning
    forever. `unit_size` is the sweep-grid partition width (lanes per
    unit) used by the entry points that CREATE the manifest — joiners
    inherit the manifest's partition."""

    directory: str | pathlib.Path
    host_id: str = dataclasses.field(default_factory=default_host_id)
    lease_ttl_seconds: float = DEFAULT_TTL_SECONDS
    heartbeat_seconds: Optional[float] = None
    poll_seconds: float = 0.25
    #: Abort when NO fleet-wide progress (claims here, publishes
    #: anywhere) is observed for this long — a stuck-store bound, not a
    #: total-runtime cap: steady progress resets it, so arbitrarily
    #: long sweeps run as long as units keep landing.
    max_wait_seconds: float = 600.0
    unit_size: int = 64
    #: Soft unit affinity: this host claims its preferred units first,
    #: and defers claiming a VIRGIN (never-leased) foreign unit until
    #: `poach_after_seconds` after its own preferred work is done —
    #: spreading hosts across the grid instead of stampeding the front.
    #: STEALING an expired/torn lease is never deferred (host-loss
    #: recovery must not wait on politeness). Empty = no affinity.
    preferred_units: tuple = ()
    poach_after_seconds: float = 0.0
    #: Cross-engine numerics-canary fraction threaded into each unit's
    #: local :class:`..resilience.supervisor.SweepSupervisor` (see its
    #: ``canary_fraction``): selected units re-execute on the demoted
    #: rung, fingerprints compare epoch-by-epoch, and the per-unit
    #: canary/drift counts ride the host ledger's ``unit_ok`` records
    #: into :class:`..fabric.health.FleetHealthReport`. 0 disables.
    canary_fraction: float = 0.0
    #: AOT executable-cache directory (:mod:`..simulation.aot`),
    #: typically ON the shared store's filesystem so every host of the
    #: fleet shares one artifact set: the host preloads its unit-shaped
    #: executables BEFORE claiming its first lease (a lease must not
    #: burn TTL on a compile another host already published), and every
    #: miss it does compile is published for the next host. None
    #: (default) leaves the legacy always-compile path untouched.
    executable_cache_dir: Optional[str] = None
    #: Segmented flight-recorder rotation for the host bundle
    #: (:class:`..telemetry.flight.RotationPolicy`): ``True`` enables
    #: the defaults, a policy instance pins thresholds, ``None``
    #: (default) defers to the ``YUMA_TPU_FLIGHT_ROTATE`` env opt-in —
    #: rotation stays OFF unless requested, so existing monolithic
    #: host bundles are untouched.
    flight_rotation: object = None

    def heartbeat_interval(self) -> float:
        if self.heartbeat_seconds is not None:
            return self.heartbeat_seconds
        return self.lease_ttl_seconds / 3.0


class _Heartbeat(threading.Thread):
    """Renews one claimed lease until stopped. A renewal that raises the
    typed `LeaseExpired` (the claim was stolen after expiry or a torn
    record) sets `lost` and stops — the owner checks the flag before
    publishing."""

    def __init__(self, leases: LeaseStore, unit: int, interval: float):
        super().__init__(name=f"lease-heartbeat-u{unit}", daemon=True)
        self.leases = leases
        self.unit = unit
        self.interval = interval
        self.lost = False
        self._stop_event = threading.Event()

    def run(self) -> None:
        while not self._stop_event.wait(self.interval):
            try:
                self.leases.renew(self.unit)
            except LeaseExpired:
                self.lost = True
                return
            except Exception:
                # A transient shared-store hiccup must not kill the
                # heartbeat — the NEXT renewal may succeed within TTL.
                logger.warning(
                    "lease heartbeat for unit %d failed transiently",
                    self.unit,
                    exc_info=True,
                )

    def stop(self) -> None:
        self._stop_event.set()
        self.join(timeout=10.0)


@dataclasses.dataclass(frozen=True)
class FleetHostSummary:
    """One host's share of a fleet sweep, as seen from inside it."""

    host_id: str
    units_published: int
    units_stolen: int
    units_abandoned: int
    units_duplicate: int


class FleetHost:
    """One process's fleet participation (see the module docstring)."""

    def __init__(self, config: FleetConfig):
        self.config = config
        self.store = FleetStore(config.directory)
        self.leases = LeaseStore(
            self.store.leases_dir,
            config.host_id,
            ttl_seconds=config.lease_ttl_seconds,
        )
        self.host_dir = self.store.host_dir(config.host_id)
        from yuma_simulation_tpu.telemetry.flight import (
            RotationPolicy,
            rotation_from_env,
        )
        from yuma_simulation_tpu.telemetry.ops import OpsPlane

        fr = config.flight_rotation
        if fr is True:
            self.rotation = RotationPolicy()
        elif fr:
            self.rotation = fr
        else:
            self.rotation = rotation_from_env()
        #: Shared live-ops mixin (same surface the serve tier exposes
        #: over HTTP): `ops.debug_vars()` / `ops.debug_spans()` /
        #: `ops.debug_profile()` against the host bundle. The active
        #: run is attached by :meth:`run_units` for span stitching.
        self.ops = OpsPlane(self.host_dir)
        self._numerics_records: list = []
        if config.executable_cache_dir:
            from yuma_simulation_tpu.simulation.aot import (
                configure_executable_cache,
            )

            configure_executable_cache(config.executable_cache_dir)

    def preload_executables(
        self,
        shapes,
        yuma_version: str,
        *,
        batch: int = 1,
        quarantine: bool = True,
        config=None,
        dtype=None,
    ) -> int:
        """Resolve unit-shaped executables BEFORE the first lease claim
        (:func:`..simulation.aot.preload_shapes`): a cache hit makes
        this host dispatch-ready in milliseconds; a miss pays the AOT
        compile NOW — outside any lease TTL, so a freshly claimed unit
        never stalls its heartbeat window on a compile another host
        already published. `config`/`dtype` must be the sweep's own —
        they select the compiled program. No-op (0) when no cache is
        active."""
        from yuma_simulation_tpu.simulation.aot import (
            active_cache,
            preload_shapes,
        )

        if active_cache() is None:
            return 0
        return preload_shapes(
            shapes,
            yuma_version=yuma_version,
            batch=batch,
            quarantine=quarantine,
            config=config,
            dtype=dtype,
            # Fleet units ALWAYS dispatch the batched program, even at
            # one lane (stack_scenarios yields [1, E, V, M]).
            batched=True,
            label=f"fleet:{self.config.host_id}",
        )

    def run_units(
        self,
        compute: Callable[[int, int, int], dict],
        *,
        num_units: int,
        unit_lanes: Sequence,
        tag: str,
        config_fingerprint: dict,
        result_keys: Sequence[str] = ("dividends",),
    ) -> FleetHostSummary:
        """Work-steal until every unit in the store has a verified
        result. `compute(idx, lo, hi)` produces one unit's arrays
        (keys in `result_keys` are published) plus underscore-prefixed
        bookkeeping (engine used, recovery counts, quarantine
        provenance) folded into the host ledger's ``unit_ok`` record.
        """
        from yuma_simulation_tpu.resilience.supervisor import FailureLedger
        from yuma_simulation_tpu.telemetry import (
            FlightRecorder,
            get_registry,
            span,
        )
        from yuma_simulation_tpu.telemetry.propagation import (
            TraceContext,
            continue_trace,
            current_trace_context,
            span_prefix_for,
        )

        # Sweep-level trace continuity: the ambient context (an active
        # driver run, or the env a drill driver handed this subprocess)
        # is stamped into the write-once manifest; joiners with no
        # ambient trace inherit the manifest's, so every host of one
        # fleet sweep continues ONE trace instead of minting orphans.
        ctx = current_trace_context()
        if ctx is None:
            ctx = TraceContext.from_env()
        manifest = self.store.ensure_manifest(
            num_units=num_units,
            unit_lanes=unit_lanes,
            tag=tag,
            config=config_fingerprint,
            trace=ctx.to_manifest() if ctx is not None else None,
        )
        if ctx is None:
            ctx = TraceContext.from_manifest(manifest)
        ledger = FailureLedger(self.host_dir / "ledger.jsonl")
        registry = get_registry()
        published = stolen = abandoned = duplicates = 0
        #: This host's numerics records (fleet-global coordinates, from
        #: `compute`'s ``_numerics``), published into the host bundle's
        #: numerics.jsonl alongside spans/ledger/metrics.
        self._numerics_records: list = []
        cfg = self.config
        with continue_trace(
            ctx, prefix=span_prefix_for(cfg.host_id)
        ) as run:
            self.ops.run = run
            if self.rotation is not None:
                try:
                    FlightRecorder(
                        self.host_dir, rotation=self.rotation
                    ).mark_run_open(run.run_id)
                except Exception:
                    logger.warning(
                        "fleet host rotation open failed for %s",
                        self.host_dir,
                        exc_info=True,
                    )
            try:
                with span(
                    f"host:{cfg.host_id}", units=num_units, fleet=tag
                ):
                    ledger.append(
                        "host_started", host=cfg.host_id, units=num_units
                    )
                    deadline_t = time.monotonic() + cfg.max_wait_seconds
                    preferred = set(cfg.preferred_units)
                    own_work_done_at: Optional[float] = None
                    last_pending: Optional[tuple] = None
                    while True:
                        # Shallow scan (existence only) in the hot loop;
                        # the completion barrier below re-verifies every
                        # result in full, so a corrupt-but-present unit
                        # still requeues — without the poll loop
                        # re-hashing every published byte each pass.
                        pending = self.store.pending_units(deep=False)
                        if not pending:
                            pending = self.store.pending_units()
                            if not pending:
                                break
                        # The stall bound resets on fleet-wide progress
                        # (the pending set shrinking covers OTHER hosts'
                        # publishes too): it aborts a wedged store, not
                        # a legitimately long sweep.
                        if tuple(pending) != last_pending:
                            last_pending = tuple(pending)
                            deadline_t = (
                                time.monotonic() + cfg.max_wait_seconds
                            )
                        if time.monotonic() > deadline_t:
                            raise TimeoutError(
                                f"fleet host {cfg.host_id} saw no fleet "
                                f"progress for {cfg.max_wait_seconds}s "
                                f"with units {pending} outstanding "
                                f"(store {self.store.directory})"
                            )
                        candidates = self._claim_candidates(
                            pending, preferred, own_work_done_at
                        )
                        if (
                            preferred
                            and own_work_done_at is None
                            and not any(u in preferred for u in pending)
                        ):
                            own_work_done_at = time.monotonic()
                        progressed = False
                        for unit in candidates:
                            # Re-check right before claiming: another
                            # host may have published while we walked
                            # the pending list.
                            if self.store.verify_result(unit):
                                progressed = True
                                continue
                            claim = self.leases.try_claim(unit)
                            if claim is None:
                                continue
                            progressed = True
                            outcome = self._run_claimed_unit(
                                unit,
                                claim,
                                compute,
                                unit_lanes[unit],
                                ledger,
                                result_keys,
                            )
                            if outcome == "published":
                                published += 1
                            elif outcome == "abandoned":
                                abandoned += 1
                            elif outcome == "duplicate":
                                duplicates += 1
                            if claim.generation > 0:
                                stolen += 1
                        if not progressed:
                            time.sleep(cfg.poll_seconds)
                    ledger.append(
                        "host_finished",
                        host=cfg.host_id,
                        published=published,
                        stolen=stolen,
                        abandoned=abandoned,
                        duplicates=duplicates,
                    )
                    log_event(
                        logger,
                        "fleet_host_finished",
                        level=logging.INFO,
                        host=cfg.host_id,
                        published=published,
                        stolen=stolen,
                        abandoned=abandoned,
                        duplicates=duplicates,
                    )
            finally:
                # The host bundle publishes on failure too (the
                # supervisor's rule): a crashed host's spans and ledger
                # are exactly what the fleet post-mortem needs, and
                # every record written so far must resolve for
                # `obsreport --check`.
                try:
                    recorder = FlightRecorder(
                        self.host_dir, rotation=self.rotation
                    )
                    recorder.record(run, registry=registry)
                    recorder.record_numerics(
                        self._numerics_records, run_id=run.run_id
                    )
                    if self.rotation is not None:
                        recorder.mark_run_closed(run.run_id)
                        recorder.seal_live_segment()
                except Exception:
                    logger.warning(
                        "fleet host bundle publish failed for %s",
                        self.host_dir,
                        exc_info=True,
                    )
        return FleetHostSummary(
            host_id=cfg.host_id,
            units_published=published,
            units_stolen=stolen,
            units_abandoned=abandoned,
            units_duplicate=duplicates,
        )

    def _claim_candidates(
        self,
        pending: Sequence[int],
        preferred: set,
        own_work_done_at: Optional[float],
    ) -> list[int]:
        """The units this host should try to claim this scan, in order:
        its preferred units first; foreign units with a STEALABLE lease
        always (host-loss recovery never waits); virgin foreign units
        only after the poach grace has elapsed since this host's own
        preferred work completed. No affinity -> everything pending."""
        if not preferred:
            return list(pending)
        mine = [u for u in pending if u in preferred]
        foreign = [u for u in pending if u not in preferred]
        out = list(mine)
        poach_ok = (
            own_work_done_at is not None
            and (time.monotonic() - own_work_done_at)
            >= self.config.poach_after_seconds
        )
        for unit in foreign:
            info = self.leases.read(unit)
            if info is not None and self.leases.is_stealable(info):
                out.append(unit)
            elif info is None and poach_ok:
                out.append(unit)
        return out

    # -- one claimed unit ----------------------------------------------

    def _run_claimed_unit(
        self,
        unit: int,
        claim: ClaimedLease,
        compute: Callable,
        lanes,
        ledger,
        result_keys: Sequence[str],
    ) -> str:
        from yuma_simulation_tpu.resilience import faults
        from yuma_simulation_tpu.telemetry import span

        cfg = self.config
        lo, hi = int(lanes[0]), int(lanes[1])
        with span(
            f"fleetunit{unit}",
            lanes=[lo, hi],
            generation=claim.generation,
            host=cfg.host_id,
        ):
            if claim.generation > 0:
                # We stole this unit: the prior holder is lost (or its
                # claim record was corrupt). One fleet-level requeue
                # record — the host analogue of event=mesh_degraded.
                ledger.append(
                    "unit_stolen",
                    unit=unit,
                    generation=claim.generation,
                    prior_host=claim.stolen_from,
                    host=cfg.host_id,
                )
                log_event(
                    logger,
                    "host_lost",
                    host=claim.stolen_from or "<torn lease>",
                    unit=unit,
                    stolen_by=cfg.host_id,
                )
            ledger.append(
                "unit_claimed",
                unit=unit,
                host=cfg.host_id,
                generation=claim.generation,
                lanes=[lo, hi],
            )
            # Deterministic drill hook: a simulated host loss SIGKILLs
            # here — after the claim is durably ledgered (so survivors
            # can see what died holding what), before any compute.
            faults.maybe_crash_host(unit)
            heartbeat = _Heartbeat(
                self.leases, unit, cfg.heartbeat_interval()
            )
            heartbeat.start()
            try:
                out = compute(unit, lo, hi)
            finally:
                heartbeat.stop()
            if heartbeat.lost or not self.leases.still_owner(unit):
                # The lease was stolen mid-compute (expiry under a long
                # stall, or a torn record). The unit belongs to the
                # stealer now; publishing would race for nothing — the
                # result is deterministic either way.
                ledger.append(
                    "unit_abandoned",
                    unit=unit,
                    host=cfg.host_id,
                    reason="lease_lost",
                )
                return "abandoned"
            was_published = self.store.publish_result(
                unit, {k: np.asarray(out[k]) for k in result_keys}
            )
            if not was_published:
                # At-most-once publish: someone (a pre-steal holder that
                # finished in the race window) already published a
                # verified result. Ours is bitwise the same; suppress.
                ledger.append(
                    "unit_duplicate", unit=unit, host=cfg.host_id
                )
                self.leases.release(unit)
                return "duplicate"
            self._numerics_records.extend(out.get("_numerics") or ())
            ledger.append(
                "unit_ok",
                unit=unit,
                host=cfg.host_id,
                lanes=[lo, hi],
                generation=claim.generation,
                attempts=int(out.get("_attempts", 1)),
                engine=out.get("_engine", "xla"),
                stalls=int(out.get("_stalls", 0)),
                demotions=int(out.get("_demotions", 0)),
                mesh_shrinks=int(out.get("_mesh_shrinks", 0)),
                canaries=int(out.get("_canaries", 0)),
                drifts=int(out.get("_drifts", 0)),
                quarantined=out.get("_quarantined", []),
            )
            self.leases.release(unit)
            return "published"


# ---------------------------------------------------------------- entries


def _fleet_canary_fraction(fraction: float, idx: int) -> float:
    """Per-unit canary fraction for fleet unit `idx`: the stride
    selection has to happen at FLEET scope, because each fleet unit's
    local supervisor sees exactly one unit (local idx 0) and would
    otherwise canary every unit for any fraction > 0. Mirrors
    `SweepSupervisor._canary_selected`'s deterministic stride (the
    shared `canary_stride` spelling) so a re-run canaries the same
    fleet units."""
    from yuma_simulation_tpu.resilience.supervisor import canary_stride

    if fraction <= 0.0:
        return 0.0
    return 1.0 if idx % canary_stride(fraction) == 0 else 0.0


def _globalize_numerics(records, idx: int, lo: int) -> list:
    """Re-stamp a unit-local supervisor's numerics records with the
    FLEET unit index and global lane bounds, so the merged stream
    speaks one coordinate system (the quarantine-provenance rule,
    applied to the numerics stream)."""
    out = []
    for rec in records or ():
        rec = dict(rec)
        rec["unit"] = idx
        lanes = rec.get("lanes") or [0, 0]
        rec["lanes"] = [lo + int(lanes[0]), lo + int(lanes[1])]
        out.append(rec)
    return out


def partition_lanes(n: int, unit_size: int) -> list[tuple[int, int]]:
    """Contiguous `(lo, hi)` unit bounds covering `range(n)` — the same
    partition rule as `SweepSupervisor._partition`, fixed in the fleet
    manifest so every host agrees on the unit map."""
    if n < 1:
        raise ValueError("cannot run an empty fleet sweep")
    if unit_size < 1:
        raise ValueError("unit_size must be >= 1")
    return [
        (lo, min(lo + unit_size, n)) for lo in range(0, n, unit_size)
    ]


def run_fleet_batch(
    scenarios,
    yuma_version: str,
    fleet: FleetConfig | str | pathlib.Path,
    *,
    config=None,
    dtype=None,
    tag: str = "",
    supervisor=None,
    finalize: bool = True,
) -> dict:
    """Run a scenario-batch sweep as this process's share of a FLEET:
    the fleet analogue of :meth:`..resilience.supervisor.SweepSupervisor
    .run_batch`, with the same output contract plus the fleet report.

    Every participating host calls this with the SAME scenarios/version/
    config against the same store directory (the manifest fingerprint
    enforces agreement); each claims units through the lease protocol
    and computes them through its local supervisor. Returns
    ``{"dividends": [B, E, V], "quarantine": QuarantineReport, "report":
    FleetHealthReport, "host": FleetHostSummary}`` once EVERY unit of
    the sweep is published (work other hosts did included).

    `finalize=False` skips the fleet-report publish and the result
    collection (used by the simulated-host drill workers, whose driver
    finalizes once after all hosts exit)."""
    import jax.numpy as jnp

    from yuma_simulation_tpu.fabric.health import (
        publish_fleet_report,
        quarantine_entries,
    )
    from yuma_simulation_tpu.resilience.guards import QuarantineReport
    from yuma_simulation_tpu.resilience.supervisor import SweepSupervisor

    if not isinstance(fleet, FleetConfig):
        fleet = FleetConfig(directory=fleet)
    dtype = jnp.float32 if dtype is None else dtype
    scenarios = list(scenarios)
    lanes = partition_lanes(len(scenarios), fleet.unit_size)
    tag = tag or f"fleet_batch:{yuma_version}"

    def compute(idx: int, lo: int, hi: int) -> dict:
        sup = supervisor if supervisor is not None else SweepSupervisor(
            directory=None,
            unit_size=fleet.unit_size,
            canary_fraction=_fleet_canary_fraction(
                fleet.canary_fraction, idx
            ),
        )
        out = sup.run_batch(
            scenarios[lo:hi],
            yuma_version,
            config,
            dtype=dtype,
            tag=f"{tag}:fleetunit{idx}",
        )
        rep = out["report"]
        return {
            "dividends": np.asarray(out["dividends"]),
            "_engine": ",".join(rep.engines_used),
            "_attempts": 1 + rep.units_retried,
            "_stalls": rep.stalls_killed,
            "_demotions": rep.engine_demotions,
            "_mesh_shrinks": rep.mesh_shrinks,
            "_canaries": rep.canaries_run,
            "_drifts": rep.drift_events,
            "_numerics": _globalize_numerics(
                out.get("numerics_records"), idx, lo
            ),
            # Globalize the slice-local quarantine provenance: the
            # fleet ledger speaks global lane indices everywhere.
            "_quarantined": [
                [lo + e.case, e.epoch, e.tensor]
                for e in out["quarantine"].entries
            ],
        }

    host = FleetHost(fleet)
    if scenarios and fleet.executable_cache_dir:
        # Preload the unit-shaped executables BEFORE the first lease
        # claim: hits make this host dispatch-ready in milliseconds;
        # misses pay the compile outside any lease TTL and publish for
        # every other host on the shared store. The sweep's OWN
        # config/dtype thread through (they select the compiled
        # program), and both distinct unit widths — the full units and
        # the trailing remainder — are warmed. Homogeneous-suite shapes
        # only: a mixed suite's per-unit shapes are not known until
        # claim time, and preload must stay best-effort.
        shapes = {np.shape(s.weights) for s in scenarios}
        if len(shapes) == 1:
            widths = {min(fleet.unit_size, len(scenarios))}
            if len(scenarios) % fleet.unit_size:
                widths.add(len(scenarios) % fleet.unit_size)
            for width in sorted(widths):
                host.preload_executables(
                    sorted(shapes),
                    yuma_version,
                    batch=width,
                    config=config,
                    dtype=dtype,
                )
    summary = host.run_units(
        compute,
        num_units=len(lanes),
        unit_lanes=lanes,
        tag=tag,
        config_fingerprint={
            "driver": "run_fleet_batch",
            "version": yuma_version,
            "num_scenarios": len(scenarios),
            "unit_size": fleet.unit_size,
            "dtype": str(np.dtype(dtype)) if dtype is not None else None,
        },
        result_keys=("dividends",),
    )
    if not finalize:
        return {"host": summary}
    report = publish_fleet_report(host.store)
    entries = quarantine_entries(host.store)
    return {
        "dividends": host.store.collect("dividends"),
        "quarantine": QuarantineReport(
            entries=tuple(entries), num_cases=len(scenarios)
        ),
        "report": report,
        "host": summary,
    }


def run_fleet_grid(
    scenario,
    yuma_version: str,
    fleet: FleetConfig | str | pathlib.Path,
    *,
    axes: Optional[dict] = None,
    configs=None,
    points: Optional[list] = None,
    tag: str = "",
    supervisor=None,
    finalize: bool = True,
    initial_state: Optional[dict] = None,
    epoch_offset: int = 0,
) -> dict:
    """Run a hyperparameter grid (or a Monte-Carlo parameter sample —
    any `axes` value lists, random draws included) as this process's
    share of a FLEET: the fleet analogue of
    :meth:`..resilience.supervisor.SweepSupervisor.run_grid`, closing
    the ROADMAP item 4 residual (fleet drivers for generated sweeps).

    `axes` maps config field names to value lists exactly as
    :func:`..simulation.sweep.config_grid` takes them; every
    participating host must call with the SAME scenario/version/axes
    against the same store (the manifest fingerprint enforces the grid
    shape). Alternatively pass a pre-built batched `configs` (+ its
    `points` list) — e.g. a seeded Monte-Carlo sample — which every
    host must construct identically (pass the seed, not the sample,
    between hosts). Grid points partition into `fleet.unit_size` units;
    each unit re-slices the batched config pytree and computes through
    the local supervisor, inheriting deadline/ladder/quarantine.

    Returns ``{"dividends": [P, E, V], "quarantine": QuarantineReport,
    "report": FleetHealthReport, "host": FleetHostSummary, "points":
    [...]}`` once every unit is published. `finalize=False` skips the
    report publish + collection (drill workers).

    `initial_state` / `epoch_offset` (additive) thread the engine's
    suffix-resume contract through every fleet unit — the continuous
    replay controller's incremental windows, where each unit simulates
    only the epochs past a durable watermark from the watermarked
    carry. The carry's content digest and the offset ride the manifest
    fingerprint, so every joining host must present the identical
    resume point (a host with a stale carry fails the manifest check
    instead of publishing silently different bits). Requires a
    `supervisor=` built with ``quarantine=False``."""
    import jax
    import jax.numpy as jnp

    from yuma_simulation_tpu.fabric.health import (
        publish_fleet_report,
        quarantine_entries,
    )
    from yuma_simulation_tpu.resilience.guards import QuarantineReport
    from yuma_simulation_tpu.resilience.supervisor import (
        SweepSupervisor,
        _state_digest as _supervisor_state_digest,
    )

    if not isinstance(fleet, FleetConfig):
        fleet = FleetConfig(directory=fleet)
    if configs is None:
        if not axes:
            raise ValueError(
                "run_fleet_grid needs axes={field: [values]} (or a "
                "pre-built configs batch)"
            )
        from yuma_simulation_tpu.simulation.sweep import config_grid

        axes = {k: [float(v) for v in vs] for k, vs in sorted(axes.items())}
        configs, points = config_grid(**axes)
    leaves = jax.tree.leaves(configs)
    num_points = next(
        (leaf.shape[0] for leaf in leaves if jnp.ndim(leaf) > 0), 1
    )
    lanes = partition_lanes(num_points, fleet.unit_size)
    tag = tag or f"fleet_grid:{yuma_version}"

    def compute(idx: int, lo: int, hi: int) -> dict:
        unit_cfg = jax.tree.map(
            lambda leaf: leaf[lo:hi] if jnp.ndim(leaf) > 0 else leaf,
            configs,
        )
        sup = supervisor if supervisor is not None else SweepSupervisor(
            directory=None,
            unit_size=fleet.unit_size,
            canary_fraction=_fleet_canary_fraction(
                fleet.canary_fraction, idx
            ),
            # Suffix-resume units cannot arm the non-finite guard (it
            # rides a monolithic scan carry) — matching run_grid's own
            # contract rather than raising three layers down.
            quarantine=initial_state is None,
        )
        out = sup.run_grid(
            scenario,
            yuma_version,
            unit_cfg,
            tag=f"{tag}:fleetunit{idx}",
            initial_state=initial_state,
            epoch_offset=epoch_offset,
        )
        rep = out["report"]
        return {
            "dividends": np.asarray(out["dividends"]),
            "_engine": ",".join(rep.engines_used),
            "_attempts": 1 + rep.units_retried,
            "_stalls": rep.stalls_killed,
            "_demotions": rep.engine_demotions,
            "_mesh_shrinks": rep.mesh_shrinks,
            "_canaries": rep.canaries_run,
            "_drifts": rep.drift_events,
            "_numerics": _globalize_numerics(
                out.get("numerics_records"), idx, lo
            ),
            "_quarantined": [
                [lo + e.case, e.epoch, e.tensor]
                for e in out["quarantine"].entries
            ],
        }

    host = FleetHost(fleet)
    summary = host.run_units(
        compute,
        num_units=len(lanes),
        unit_lanes=lanes,
        tag=tag,
        config_fingerprint={
            "driver": "run_fleet_grid",
            "version": yuma_version,
            "num_points": int(num_points),
            "unit_size": fleet.unit_size,
            "axes": axes if axes is not None else "prebuilt-configs",
            "shape": [int(d) for d in np.shape(scenario.weights)],
            # Additive suffix-resume identity (absent for classic
            # from-zero grids, keeping existing manifests joinable).
            **(
                {
                    "epoch_offset": int(epoch_offset),
                    "initial_state": _supervisor_state_digest(
                        initial_state
                    ),
                }
                if initial_state is not None or epoch_offset
                else {}
            ),
        },
        result_keys=("dividends",),
    )
    if not finalize:
        return {"host": summary}
    report = publish_fleet_report(host.store)
    entries = quarantine_entries(host.store)
    return {
        "dividends": host.store.collect("dividends"),
        "quarantine": QuarantineReport(
            entries=tuple(entries), num_cases=int(num_points)
        ),
        "report": report,
        "host": summary,
        "points": points,
    }


def run_fleet_artifacts(
    labels: Sequence[str],
    build: Callable[[str], bytes],
    fleet: FleetConfig | str | pathlib.Path,
    *,
    tag: str,
    config_fingerprint: dict,
) -> dict:
    """Coordinate a per-label artifact build (CSV sheets, HTML tables)
    across concurrent CLI invocations: each label is one lease-claimed
    unit, `build(label) -> bytes` runs at most once per label across
    the whole fleet (a dying builder's label is requeued via lease
    expiry), and every invocation returns the COMPLETE ``{label:
    bytes}`` map once all units are published — so N processes pointed
    at one store split the sweep and each still writes the full
    artifact set."""
    from yuma_simulation_tpu.fabric.health import publish_fleet_report

    if not isinstance(fleet, FleetConfig):
        fleet = dataclasses.replace(
            FleetConfig(directory=fleet), unit_size=1
        )
    labels = [str(label) for label in labels]

    def compute(idx: int, lo: int, hi: int) -> dict:
        data = build(labels[idx])
        return {
            "artifact": np.frombuffer(bytearray(data), dtype=np.uint8),
        }

    host = FleetHost(fleet)
    host.run_units(
        compute,
        num_units=len(labels),
        unit_lanes=[(i, i + 1) for i in range(len(labels))],
        tag=tag,
        config_fingerprint=dict(config_fingerprint, labels=labels),
        result_keys=("artifact",),
    )
    publish_fleet_report(host.store)
    out = {}
    for i, label in enumerate(labels):
        loaded = host.store.load_result(i)
        assert loaded is not None  # run_units returned => verified
        out[label] = loaded["artifact"].tobytes()
    return out


def run_fleet_case(
    case,
    yuma_version: str,
    yuma_config=None,
    *,
    fleet: FleetConfig | str | pathlib.Path,
    supervised: bool = True,
) -> tuple:
    """One `run_simulation` executed under fleet coordination: the
    single case is one work unit in the shared store, so N processes
    invoked concurrently with the same store run it EXACTLY once
    (lease-arbitrated), survive the runner dying mid-simulation (lease
    expiry -> any peer re-executes), and all return the same published
    triple. The v1 `run_simulation(fleet=...)` knob routes here."""
    from yuma_simulation_tpu.fabric.health import publish_fleet_report
    from yuma_simulation_tpu.simulation.engine import simulate

    if not isinstance(fleet, FleetConfig):
        fleet = FleetConfig(directory=fleet)

    supervision = {}
    if supervised:
        from yuma_simulation_tpu.resilience.retry import (
            default_retry_policy,
        )
        from yuma_simulation_tpu.resilience.supervisor import (
            default_deadline,
        )

        supervision = {
            "retry_policy": default_retry_policy(),
            "deadline": default_deadline(),
        }

    def compute(idx: int, lo: int, hi: int) -> dict:
        result = simulate(
            case,
            yuma_version,
            yuma_config,
            save_bonds=True,
            save_incentives=True,
            **supervision,
        )
        return {
            "dividends": np.asarray(result.dividends),
            "bonds": np.asarray(result.bonds),
            "incentives": np.asarray(result.incentives),
            "_engine": "xla",
        }

    host = FleetHost(fleet)
    host.run_units(
        compute,
        num_units=1,
        unit_lanes=[(0, 1)],
        tag=f"fleet_case:{yuma_version}:{getattr(case, 'name', 'case')}",
        config_fingerprint={
            "driver": "run_fleet_case",
            "version": yuma_version,
            "case": getattr(case, "name", str(case)),
            "shape": [int(d) for d in np.shape(case.weights)],
        },
        result_keys=("dividends", "bonds", "incentives"),
    )
    publish_fleet_report(host.store)
    loaded = host.store.load_result(0)
    assert loaded is not None  # run_units returned => unit 0 verified
    dividends = loaded["dividends"]
    dividends_per_validator = {
        validator: [float(x) for x in dividends[:, i]]
        for i, validator in enumerate(case.validators)
    }
    return (
        dividends_per_validator,
        list(loaded["bonds"]),
        list(loaded["incentives"]),
    )
