"""Fleet fabric: the work-stealing multi-host sweep scheduler.

The distributed tier above the single-host resilience stack (ROADMAP
item 4). A sweep grid is partitioned into pure, idempotent work units
(the manifest), hosts coordinate through a shared filesystem store with
LEASE-BASED claiming — atomic claim files, heartbeat-renewed, expiry-
driven stealing — and each host computes its claimed units through its
local :class:`..resilience.SweepSupervisor`, so every unit inherits the
deadline watchdog, engine ladder, NaN quarantine and elastic mesh. Any
surviving host requeues a dead host's units; results are content-
addressed and bitwise-deterministic, so duplicate execution is harmless
and publish is at-most-once.

- :mod:`.lease` — the claim/heartbeat/steal protocol;
- :mod:`.store` — manifest + per-unit results + per-host bundles;
- :mod:`.scheduler` — the host loop and the `run_fleet_batch` /
  `run_fleet_grid` / `run_fleet_case` entry points;
- :mod:`.health` — the merged-ledger :class:`FleetHealthReport` and the
  `obsreport --check` fleet gate;
- :mod:`.simhost` — multiprocess simulated hosts + the pod-level chaos
  drill (CPU CI).

See README.md "Fleet sweeps" for the operator-facing contract.
"""

from yuma_simulation_tpu.fabric.health import (  # noqa: F401
    FleetDegradation,
    FleetHealthReport,
    build_fleet_report,
    check_fleet,
    merged_ledger,
    publish_fleet_report,
)
from yuma_simulation_tpu.fabric.lease import (  # noqa: F401
    ClaimedLease,
    LeaseInfo,
    LeaseStore,
)
from yuma_simulation_tpu.fabric.scheduler import (  # noqa: F401
    FleetConfig,
    FleetHost,
    FleetHostSummary,
    partition_lanes,
    run_fleet_artifacts,
    run_fleet_batch,
    run_fleet_case,
    run_fleet_grid,
)
from yuma_simulation_tpu.fabric.store import (  # noqa: F401
    FleetStore,
    is_fleet_store,
)
