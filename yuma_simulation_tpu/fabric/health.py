"""Fleet health: the merged-ledger report and its consistency gate.

Every fleet host writes its own crash-safe
:class:`..resilience.supervisor.FailureLedger` (plus a flight-recorder
bundle) under ``hosts/<host_id>/``; nothing at fleet level is recorded
anywhere else. The :class:`FleetHealthReport` is therefore DERIVED —
a pure function of the merged per-host ledgers plus the result store —
and :func:`check_fleet` is the cross-check: recompute the report from
the ledgers, compare it with the published one, and verify that every
claim on disk resolves to a ledger record (which the per-host bundle
check in turn resolves to a telemetry span). The same
shrink-and-continue semantics as the elastic mesh apply one level up:
:data:`FleetDegradation` IS :class:`..parallel.mesh.MeshDegradation`
with hosts in place of devices, and the surviving roster comes from the
same :func:`..parallel.mesh.surviving_members` filter.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import pathlib
from typing import Optional

from yuma_simulation_tpu.fabric.store import (
    FLEET_REPORT_NAME,
    FleetStore,
    is_fleet_store,
)
from yuma_simulation_tpu.parallel.mesh import (
    MeshDegradation,
    surviving_members,
)
from yuma_simulation_tpu.utils.checkpoint import (
    publish_atomic,
    read_jsonl_tolerant,
)

logger = logging.getLogger(__name__)

#: One elastic shrink of the fleet's host roster — the same record shape
#: as a mesh shrink, one level up (``from_devices``/``to_devices`` count
#: hosts, ``lost_device_ids`` carries host ids).
FleetDegradation = MeshDegradation

#: FleetHealthReport counts the merged ledgers must reproduce exactly
#: (the fleet half of ``obsreport --check``). Roster fields
#: (hosts_finished et al.) are deliberately NOT cross-checked: they keep
#: moving while late hosts exit, whereas these are fixed once every unit
#: has published.
FLEET_CROSS_CHECKED_COUNTS = (
    "units_published",
    "units_stolen",
    "units_abandoned",
    "units_duplicate",
    "stalls_killed",
    "engine_demotions",
    "mesh_shrinks",
    "lanes_quarantined",
    # 0.14.0 — numerics-canary accounting (additive: pre-0.14 reports
    # lack the keys and are skipped by the `key in published` guard).
    "canaries_run",
    "drift_events",
    # 0.24.0 — incident-intelligence accounting (additive, same guard).
    "anomalies_detected",
    "incidents_opened",
    "incidents_resolved",
)


@dataclasses.dataclass(frozen=True)
class FleetHealthReport:
    """What a fleet sweep survived — the cross-host twin of the
    single-host :class:`..resilience.supervisor.SweepHealthReport`,
    derived entirely from the merged per-host ledgers."""

    fleet: str
    num_units: int
    units_published: int
    #: hosts that appended a host_started record, sorted.
    hosts_seen: tuple
    #: hosts that also appended host_finished, sorted.
    hosts_finished: tuple
    #: started-but-never-finished hosts (crashed/preempted), sorted.
    hosts_lost: tuple
    #: distinct units whose lease was stolen after expiry/tear.
    units_stolen: int
    #: executions abandoned on a lost lease (no publish).
    units_abandoned: int
    #: executions whose publish found a verified result already there.
    units_duplicate: int
    #: summed from every accepted (unit_ok) execution:
    stalls_killed: int
    engine_demotions: int
    mesh_shrinks: int
    #: from each unit's LAST unit_ok record (the execution whose result
    #: stands in the store) — the supervisor's resume rule, fleet-wide.
    lanes_quarantined: int
    #: one roster shrink per lost host, in loss order.
    degradations: tuple = ()
    #: numerics-canary re-executions across every accepted execution
    #: (:mod:`..telemetry.numerics`), summed from the unit_ok records.
    canaries_run: int = 0
    #: canary comparisons that CONFIRMED cross-engine drift.
    drift_events: int = 0
    #: per-unit EXECUTED engine rung, from each unit's LAST unit_ok
    #: record (the execution whose result stands in the store) —
    #: `((unit, engine), ...)` sorted by unit. Closes the "pod-scale
    #: paths never show which engine actually ran" gap: the merged
    #: ledgers now answer it unit by unit.
    unit_engines: tuple = ()
    #: incident intelligence (0.24.0, additive): detector firings and
    #: correlated incident transitions across the merged host ledgers.
    anomalies_detected: int = 0
    incidents_opened: int = 0
    incidents_resolved: int = 0

    @property
    def clean(self) -> bool:
        """True iff nothing degraded fleet-wide: every host finished,
        nothing was stolen/abandoned, no unit-level recovery action
        fired, and no canary confirmed drift."""
        return not (
            self.hosts_lost
            or self.units_stolen
            or self.units_abandoned
            or self.stalls_killed
            or self.engine_demotions
            or self.mesh_shrinks
            or self.lanes_quarantined
            or self.drift_events
        )

    def to_json(self) -> dict:
        rec = dataclasses.asdict(self)
        rec["degradations"] = [
            dataclasses.asdict(d) if dataclasses.is_dataclass(d) else d
            for d in self.degradations
        ]
        return rec


def merged_ledger(store: FleetStore) -> list[dict]:
    """Every host's ledger records, merged and time-ordered — the
    fleet's single auditable history."""
    records: list[dict] = []
    for host_id in store.host_ids():
        records.extend(
            read_jsonl_tolerant(store.host_dir(host_id) / "ledger.jsonl")
        )
    records.sort(key=lambda r: float(r.get("t") or 0.0))
    return records


def quarantine_entries(store: FleetStore) -> list:
    """Global-lane quarantine provenance from each unit's LAST unit_ok
    record (the execution whose result stands in the store)."""
    from yuma_simulation_tpu.resilience.guards import QuarantineEntry

    last_ok: dict[int, dict] = {}
    for rec in merged_ledger(store):
        if rec.get("event") == "unit_ok" and "unit" in rec:
            last_ok[rec["unit"]] = rec
    entries = []
    for rec in last_ok.values():
        for item in rec.get("quarantined", ()):
            if isinstance(item, (list, tuple)) and len(item) == 3:
                entries.append(
                    QuarantineEntry(
                        case=int(item[0]),
                        epoch=int(item[1]),
                        tensor=str(item[2]),
                    )
                )
    entries.sort(key=lambda e: (e.case, e.epoch))
    return entries


def build_fleet_report(
    store: FleetStore | str | pathlib.Path,
) -> FleetHealthReport:
    """Derive the report from the merged ledgers + result store (pure;
    no mutation — :func:`publish_fleet_report` persists it)."""
    store = store if isinstance(store, FleetStore) else FleetStore(store)
    manifest = store.manifest()
    records = merged_ledger(store)

    def hosts(event: str) -> set:
        return {
            r.get("host")
            for r in records
            if r.get("event") == event and r.get("host")
        }

    seen = hosts("host_started")
    finished = hosts("host_finished")
    # Loss order follows the steal records (the survivors' view of the
    # failure); hosts that started and never finished but were never
    # stolen from (e.g. crashed after their last publish) append after.
    lost_in_order: list = []
    for r in records:
        if r.get("event") == "unit_stolen":
            prior = r.get("prior_host")
            if prior and prior in seen and prior not in finished:
                if prior not in lost_in_order:
                    lost_in_order.append(prior)
    for host in sorted(seen - finished):
        if host not in lost_in_order:
            lost_in_order.append(host)

    degradations = []
    roster = sorted(seen)
    for host in lost_in_order:
        survivors = surviving_members(roster, [host])
        degradations.append(
            FleetDegradation(
                from_devices=len(roster),
                to_devices=len(survivors),
                lost_device_ids=(host,),
                reason="host_lost",
            )
        )
        roster = survivors

    oks = [r for r in records if r.get("event") == "unit_ok"]
    last_ok: dict[int, dict] = {}
    for r in oks:
        if "unit" in r:
            last_ok[r["unit"]] = r
    published = [
        u
        for u in range(manifest["num_units"])
        if store.verify_result(u)
    ]
    return FleetHealthReport(
        fleet=manifest.get("fleet", "fleet"),
        num_units=manifest["num_units"],
        units_published=len(published),
        hosts_seen=tuple(sorted(seen)),
        hosts_finished=tuple(sorted(finished)),
        hosts_lost=tuple(lost_in_order),
        units_stolen=len(
            {
                r.get("unit")
                for r in records
                if r.get("event") == "unit_stolen"
            }
        ),
        units_abandoned=sum(
            1 for r in records if r.get("event") == "unit_abandoned"
        ),
        units_duplicate=sum(
            1 for r in records if r.get("event") == "unit_duplicate"
        ),
        stalls_killed=sum(int(r.get("stalls", 0)) for r in oks),
        engine_demotions=sum(int(r.get("demotions", 0)) for r in oks),
        mesh_shrinks=sum(int(r.get("mesh_shrinks", 0)) for r in oks),
        lanes_quarantined=sum(
            len(r.get("quarantined", ())) for r in last_ok.values()
        ),
        degradations=tuple(degradations),
        canaries_run=sum(int(r.get("canaries", 0)) for r in oks),
        drift_events=sum(int(r.get("drifts", 0)) for r in oks),
        unit_engines=tuple(
            (unit, str(last_ok[unit].get("engine", "?")))
            for unit in sorted(last_ok)
        ),
        anomalies_detected=sum(
            1 for r in records if r.get("event") == "anomaly_detected"
        ),
        incidents_opened=sum(
            1 for r in records if r.get("event") == "incident_opened"
        ),
        incidents_resolved=sum(
            1 for r in records if r.get("event") == "incident_resolved"
        ),
    )


def publish_fleet_report(
    store: FleetStore | str | pathlib.Path,
) -> FleetHealthReport:
    """Derive and atomically publish ``fleet_report.json``. Called by
    whoever finalizes the sweep (the driver, or any host that observes
    completion); idempotent — the content is a pure function of the
    on-disk ledgers, so re-finalizing after stragglers exit only makes
    the roster fields MORE complete."""
    store = store if isinstance(store, FleetStore) else FleetStore(store)
    report = build_fleet_report(store)
    publish_atomic(
        store.directory / FLEET_REPORT_NAME,
        json.dumps(report.to_json(), sort_keys=True).encode(),
    )
    return report


def load_fleet_report(
    store: FleetStore | str | pathlib.Path,
) -> Optional[dict]:
    store = store if isinstance(store, FleetStore) else FleetStore(store)
    path = store.directory / FLEET_REPORT_NAME
    if not path.exists():
        return None
    try:
        return json.loads(path.read_text())
    except json.JSONDecodeError:
        logger.warning("undecodable %s in %s", FLEET_REPORT_NAME, path.parent)
        return None


def check_fleet(directory: str | pathlib.Path) -> list[str]:
    """Fleet-store consistency problems (empty list = sound):

    - every unit has a verified published result;
    - every published unit has at least one ``unit_ok`` ledger record
      (a result nobody accounts for is a phantom write);
    - every CLAIM on disk resolves to a ledger record: each live lease
      file's (host, unit) matches a ``unit_claimed`` record, and each
      unit's tombstone count equals its ``unit_stolen`` record count
      (torn lease files are tolerated — they are stealable, not sound);
    - the published ``fleet_report.json`` (when present) matches the
      ledger-derived counts exactly (:data:`FLEET_CROSS_CHECKED_COUNTS`).

    Per-host span resolution (every ledger record -> a recorded span)
    is the existing per-host bundle gate
    (:func:`..telemetry.flight.check_bundle`), which ``obsreport``
    runs alongside this.
    """
    directory = pathlib.Path(directory)
    if not is_fleet_store(directory):
        return [f"{directory} is not a fleet store (no fleet manifest)"]
    store = FleetStore(directory)
    manifest = store.manifest()
    records = merged_ledger(store)
    problems: list[str] = []

    for unit in range(manifest["num_units"]):
        if not store.verify_result(unit):
            problems.append(f"unit {unit} has no verified result")
    oks = {
        r.get("unit") for r in records if r.get("event") == "unit_ok"
    }
    for unit in store.published_units():
        if unit not in oks:
            problems.append(
                f"unit {unit} result is published but no host ledger "
                "carries a unit_ok record for it"
            )

    claimed = {
        (r.get("host"), r.get("unit"))
        for r in records
        if r.get("event") == "unit_claimed"
    }
    stolen_counts: dict[int, int] = {}
    for r in records:
        if r.get("event") == "unit_stolen" and "unit" in r:
            stolen_counts[r["unit"]] = stolen_counts.get(r["unit"], 0) + 1
    for lease_path in sorted(store.leases_dir.glob("unit_*.lease")):
        tail = lease_path.stem.split("_", 1)[1]
        if not tail.isdigit():
            continue
        unit = int(tail)
        try:
            data = json.loads(lease_path.read_text())
            host = data.get("host") if isinstance(data, dict) else None
        except (json.JSONDecodeError, OSError):
            continue  # torn lease: stealable, tolerated
        if host and (host, unit) not in claimed:
            problems.append(
                f"lease for unit {unit} names host {host!r} but no "
                "ledger carries its unit_claimed record"
            )
    tombstones: dict[int, int] = {}
    for p in store.leases_dir.glob("stale_unit_*"):
        tail = p.name.split(".", 1)[0].rsplit("_", 1)[1]
        if tail.isdigit():
            unit = int(tail)
            tombstones[unit] = tombstones.get(unit, 0) + 1
    for unit in sorted(set(tombstones) | set(stolen_counts)):
        # Every LEDGERED steal must have its durable tombstone (the
        # rename happens strictly before the record is appended, so a
        # deficit means fabricated or lost evidence). The converse is
        # tolerated: a stealer killed between its tombstone rename and
        # its ledger append leaves an EXCESS tombstone — the store is
        # still recoverable (another host re-steals and completes), and
        # flagging it would make a sound, finished sweep fail --check
        # forever with no repair path.
        if tombstones.get(unit, 0) < stolen_counts.get(unit, 0):
            problems.append(
                f"unit {unit}: {stolen_counts.get(unit, 0)} unit_stolen "
                f"ledger records but only {tombstones.get(unit, 0)} "
                "steal tombstones on disk"
            )

    published = load_fleet_report(store)
    if published is not None:
        derived = build_fleet_report(store).to_json()
        for key in FLEET_CROSS_CHECKED_COUNTS:
            if key in published and int(published[key]) != int(derived[key]):
                problems.append(
                    f"fleet_report.{key}={published[key]} but the merged "
                    f"ledgers derive {derived[key]}"
                )
    return problems
