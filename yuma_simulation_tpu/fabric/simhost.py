"""Simulated fleet hosts: the multiprocess pod-level chaos drill on CPU.

A *simulated host* is a real OS process running the real fleet scheduler
against a real shared store — only the accelerator is virtual (forced
CPU backend), following the pattern of
``tests/unit/test_distributed_multiprocess.py``. Two entry points:

- ``python -m yuma_simulation_tpu.fabric.simhost --store DIR --host-id
  H ...`` — ONE host process: builds the deterministic built-in scenario
  suite, optionally arms a :class:`..resilience.faults.FaultPlan` from
  its flags (host crash, lease tear, stall, NaN lane), and participates
  in the fleet sweep until every unit is published.
- :func:`run_drill` — the drill DRIVER: computes the unfaulted oracle
  in-process, spawns >=3 simulated hosts with one fault each (kill /
  lease tear / stall+NaN), waits them out, finalizes the fleet report,
  and VERIFIES the whole pod-level guarantee: the sweep completes, no
  unit is lost, none double-publishes, healthy lanes are
  bitwise-identical to the unfaulted run, and the
  :class:`..fabric.health.FleetHealthReport` reconciles with the merged
  ledgers (``obsreport --check`` semantics). Raises on any violation —
  the CI chaos lane and the chaos pytest battery both drive it.

Determinism notes: the scenario suite is the built-in case registry (a
pure function of nothing), unit bounds live in the write-once manifest,
and every fault is one of the deterministic hooks in
:mod:`..resilience.faults`. WHICH host executes a given unit is
scheduling-dependent (that is the point of work stealing), but unit
RESULTS are not — any healthy host produces bitwise the same bytes.
"""

from __future__ import annotations

import argparse
import os
import pathlib
import signal
import subprocess
import sys
import time

DEFAULT_VERSION = "Yuma 1 (paper)"

#: Drill geometry: 10 cases x unit_size 2 = 5 units, partitioned by
#: affinity as crash-host:[0], stall+NaN host:[1,2], tear host:[3,4].
DRILL_NUM_CASES = 10
DRILL_UNIT_SIZE = 2
DRILL_TTL = 3.0

#: The drill DRIVER's bundle directory name under ``hosts/``: the root
#: of the stitched cross-process trace (every simulated host's spans
#: chain up to the driver's ``fleet_drill`` span through the env-
#: propagated trace context).
DRIVER_HOST_ID = "driver"


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="simhost", description=__doc__.split("\n\n")[0]
    )
    p.add_argument("--store", required=True, help="shared fleet store dir")
    p.add_argument("--host-id", required=True)
    p.add_argument("--version", default=DEFAULT_VERSION)
    p.add_argument("--num-cases", type=int, default=DRILL_NUM_CASES)
    p.add_argument("--unit-size", type=int, default=DRILL_UNIT_SIZE)
    p.add_argument("--ttl", type=float, default=DRILL_TTL)
    p.add_argument("--heartbeat", type=float, default=0.5)
    p.add_argument("--poll", type=float, default=0.1)
    p.add_argument("--max-wait", type=float, default=300.0)
    p.add_argument(
        "--preferred", default="",
        help="comma-separated unit indices this host claims first",
    )
    p.add_argument("--poach-after", type=float, default=30.0)
    p.add_argument(
        "--executable-cache",
        default=None,
        metavar="DIR",
        help="AOT executable-cache directory (simulation.aot): this "
        "host preloads its unit-shaped executables before claiming its "
        "first lease, and publishes what it compiles for the fleet",
    )
    # Deadline knobs (the stall host shrinks these after its warm-up).
    p.add_argument("--deadline", type=float, default=240.0)
    p.add_argument("--grace", type=float, default=240.0)
    # Fault knobs — each maps onto one deterministic hook.
    p.add_argument("--crash-after-claims", type=int, default=0)
    p.add_argument("--tear-after-renewals", type=int, default=0)
    p.add_argument("--stall-seconds", type=float, default=0.0)
    p.add_argument("--stall-dispatches", type=int, default=0)
    p.add_argument("--nan-epoch", type=int, default=-1)
    p.add_argument("--nan-case", type=int, default=-1)
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    # Simulated hosts are CPU by definition; force the backend before
    # anything touches it (the drill driver also sets the env, but a
    # hand-launched simhost must not grab a real accelerator).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from yuma_simulation_tpu.fabric.scheduler import (
        FleetConfig,
        run_fleet_batch,
    )
    from yuma_simulation_tpu.resilience import (
        Deadline,
        FaultPlan,
        HostCrashFault,
        LeaseTearFault,
        NaNFault,
        RetryPolicy,
        StallFault,
        SweepSupervisor,
        inject_faults,
    )
    from yuma_simulation_tpu.scenarios import get_cases
    from yuma_simulation_tpu.utils import setup_logging

    setup_logging()
    cases = get_cases()[: args.num_cases]
    policy = RetryPolicy(max_attempts_per_rung=2, backoff_base=0.0, seed=0)
    preferred = tuple(
        int(u) for u in args.preferred.split(",") if u.strip() != ""
    )
    fleet = FleetConfig(
        directory=args.store,
        host_id=args.host_id,
        lease_ttl_seconds=args.ttl,
        heartbeat_seconds=args.heartbeat,
        poll_seconds=args.poll,
        max_wait_seconds=args.max_wait,
        unit_size=args.unit_size,
        preferred_units=preferred,
        poach_after_seconds=args.poach_after,
        executable_cache_dir=args.executable_cache,
    )

    plan_kwargs: dict = {}
    if args.crash_after_claims > 0:
        plan_kwargs["host_crash"] = HostCrashFault(
            after_claims=args.crash_after_claims
        )
    if args.tear_after_renewals > 0:
        plan_kwargs["lease_tear"] = LeaseTearFault(
            after_renewals=args.tear_after_renewals
        )
    if args.stall_dispatches > 0:
        plan_kwargs["stall"] = StallFault(
            seconds=args.stall_seconds, dispatches=args.stall_dispatches
        )
    if args.nan_epoch >= 0:
        plan_kwargs["nan"] = NaNFault(
            epoch=args.nan_epoch,
            case=None if args.nan_case < 0 else args.nan_case,
        )

    deadline = Deadline(args.deadline, grace_seconds=args.grace)
    if plan_kwargs.get("stall") is not None:
        # The stall host's tight deadline must only ever kill the
        # injected hold, never a machine-speed-dependent cold compile —
        # warm the unit shape (and its NaN-operand jit variant when that
        # fault is armed too) under a roomy budget first, exactly as the
        # single-host chaos drills do.
        roomy = SweepSupervisor(
            directory=None,
            unit_size=args.unit_size,
            deadline=Deadline(240.0, grace_seconds=240.0),
            retry_policy=policy,
        )
        warm_cases = cases[: args.unit_size]
        roomy.run_batch(warm_cases, args.version)
        if plan_kwargs.get("nan") is not None:
            with inject_faults(FaultPlan(nan=plan_kwargs["nan"])):
                roomy.run_batch(warm_cases, args.version)

    supervisor = SweepSupervisor(
        directory=None,
        unit_size=args.unit_size,
        deadline=deadline,
        retry_policy=policy,
    )

    def participate():
        return run_fleet_batch(
            cases,
            args.version,
            fleet,
            tag="fleet_drill",
            supervisor=supervisor,
            finalize=False,
        )

    if plan_kwargs:
        with inject_faults(FaultPlan(**plan_kwargs)):
            out = participate()
    else:
        out = participate()
    summary = out["host"]
    print(
        f"FLEET_HOST_DONE {args.host_id} "
        f"published={summary.units_published} "
        f"stolen={summary.units_stolen} "
        f"abandoned={summary.units_abandoned} "
        f"duplicates={summary.units_duplicate}",
        flush=True,
    )
    return 0


# -------------------------------------------------------------- the drill


def _spawn_host(
    store: str,
    host_args: list[str],
    out_dir: pathlib.Path,
    extra_env: dict | None = None,
):
    """One simulated host subprocess with file-backed stdio (a crashing
    host's traceback must not deadlock a pipe). `extra_env` carries the
    driver's trace context (``YUMA_TRACEPARENT``) so the host's run
    continues the drill-level trace."""
    repo = pathlib.Path(__file__).resolve().parents[2]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 1 virtual device: simhosts are unsharded
    env.update(extra_env or {})
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [str(repo), env.get("PYTHONPATH", "")] if p
    )
    host_id = host_args[host_args.index("--host-id") + 1]
    out = open(out_dir / f"{host_id}.out", "w+")
    err = open(out_dir / f"{host_id}.err", "w+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "yuma_simulation_tpu.fabric.simhost",
         "--store", store, *host_args],
        env=env,
        stdout=out,
        stderr=err,
        text=True,
    )
    return proc, out, err


def run_drill(
    directory: str | pathlib.Path,
    *,
    timeout: float = 420.0,
    version: str = DEFAULT_VERSION,
) -> dict:
    """The pod-level chaos drill (module docstring). Verifies every
    acceptance property and raises on violation; returns a summary dict
    (`report`, `oracle`, `merged`, per-host rc/stdout/stderr)."""
    import numpy as np

    from yuma_simulation_tpu.fabric.health import (
        build_fleet_report,
        check_fleet,
        merged_ledger,
        publish_fleet_report,
        quarantine_entries,
    )
    from yuma_simulation_tpu.fabric.store import FleetStore
    from yuma_simulation_tpu.telemetry.flight import (
        FlightRecorder,
        check_bundle,
        check_stitched,
        load_bundle,
    )
    from yuma_simulation_tpu.telemetry.propagation import (
        BAGGAGE_ENV,
        TRACEPARENT_ENV,
        current_trace_context,
    )
    from yuma_simulation_tpu.telemetry.runctx import RunContext, span

    target = pathlib.Path(directory)
    if target.exists() and any(target.iterdir()):
        raise SystemExit(
            f"fleet-drill target {str(target)!r} already exists and is "
            "not empty; point the drill at a fresh directory (a resumed "
            "drill exercises none of its faults)"
        )
    target.mkdir(parents=True, exist_ok=True)
    logs = target / "drill-logs"
    logs.mkdir()

    store_dir = str(target / "store")
    oracle_store_dir = str(target / "oracle-store")
    common = [
        "--version", version,
        "--num-cases", str(DRILL_NUM_CASES),
        "--unit-size", str(DRILL_UNIT_SIZE),
        "--ttl", str(DRILL_TTL),
        "--poach-after", "60.0",
    ]
    # Host roles (>=3 hosts, one fault family each): crash / stall+NaN /
    # lease tear. Affinity spreads the initial claims so each fault
    # lands regardless of startup jitter; stealing recovers the crash.
    # A fourth, UNFAULTED host runs the same sweep into its own store —
    # the oracle: computed in an identical subprocess environment so
    # "healthy lanes bitwise-identical to the unfaulted run" compares
    # like with like (the driver process may run under different jax
    # config, e.g. pytest's x64 mode).
    hosts = {
        "crash-host": (store_dir, common + [
            "--host-id", "crash-host",
            "--preferred", "0",
            "--crash-after-claims", "1",
        ]),
        "stall-host": (store_dir, common + [
            "--host-id", "stall-host",
            "--preferred", "1,2",
            "--stall-seconds", "1.0",
            "--stall-dispatches", "1",
            "--nan-epoch", "2",
            "--nan-case", "1",
            "--deadline", "0.15",
            "--grace", "60.0",
        ]),
        "tear-host": (store_dir, common + [
            "--host-id", "tear-host",
            "--preferred", "3,4",
            "--tear-after-renewals", "1",
        ]),
        "oracle-host": (oracle_store_dir, common + [
            "--host-id", "oracle-host",
        ]),
    }
    # The drill is ONE distributed trace: the driver opens the root run
    # + span, hands its context to the faulted hosts through the env
    # (the oracle runs a SEPARATE sweep into its own store and gets a
    # scrubbed env so its self-contained bundle stays self-resolving),
    # and publishes its own bundle under hosts/driver so every host
    # span's parent chain roots at the driver's run on disk.
    driver_run = RunContext()
    procs = {}
    files = []
    results = {}
    with driver_run:
        with span("fleet_drill", hosts=list(hosts)):
            ctx = current_trace_context()
            assert ctx is not None  # the driver run/span is open
            trace_env = ctx.to_env()
            scrubbed = {TRACEPARENT_ENV: "", BAGGAGE_ENV: ""}
            for host_id, (host_store, host_args) in hosts.items():
                proc, out, err = _spawn_host(
                    host_store,
                    host_args,
                    logs,
                    extra_env=(
                        scrubbed if host_id == "oracle-host" else trace_env
                    ),
                )
                procs[host_id] = proc
                files.extend([out, err])
            try:
                deadline_t = time.monotonic() + timeout
                for host_id, proc in procs.items():
                    remaining = max(1.0, deadline_t - time.monotonic())
                    rc = proc.wait(timeout=remaining)
                    results[host_id] = rc
            except subprocess.TimeoutExpired:
                for proc in procs.values():
                    proc.kill()
                raise
            finally:
                streams = {}
                for f in files:
                    f.seek(0)
                    streams[pathlib.Path(f.name).name] = f.read()
                    f.close()
    FlightRecorder(
        FleetStore(store_dir).host_dir(DRIVER_HOST_ID)
    ).record(driver_run)

    def _log(host_id: str, stream: str) -> str:
        return streams.get(f"{host_id}.{stream}", "")

    # -- verification ---------------------------------------------------
    problems: list[str] = []
    if results["crash-host"] != -signal.SIGKILL:
        problems.append(
            f"crash-host exited {results['crash-host']}, expected "
            f"SIGKILL ({-signal.SIGKILL}):\n{_log('crash-host', 'err')[-2000:]}"
        )
    for host_id in ("stall-host", "tear-host", "oracle-host"):
        if results[host_id] != 0:
            problems.append(
                f"{host_id} exited {results[host_id]}:\n"
                f"{_log(host_id, 'err')[-3000:]}"
            )
    if "kind=lease_tear" not in _log("tear-host", "err"):
        problems.append("tear-host never injected its lease tear")
    if problems:
        raise AssertionError("fleet drill host failures:\n" + "\n".join(problems))

    store = FleetStore(store_dir)
    report = publish_fleet_report(store)
    merged = merged_ledger(store)
    oracle = FleetStore(oracle_store_dir).collect("dividends")
    oracle_report = publish_fleet_report(oracle_store_dir)
    if not oracle_report.clean:
        problems.append(
            f"the unfaulted oracle run was not clean: {oracle_report}"
        )

    # The sweep completed: every unit published, none lost.
    if report.units_published != report.num_units:
        problems.append(
            f"{report.units_published}/{report.num_units} units published"
        )
    # At-most-once publish: exactly one accepted execution per unit.
    ok_units = [r["unit"] for r in merged if r.get("event") == "unit_ok"]
    if sorted(ok_units) != list(range(report.num_units)):
        problems.append(
            f"unit_ok records {sorted(ok_units)} != exactly one per unit"
        )
    # The faults all fired and were survived.
    if "crash-host" not in report.hosts_lost:
        problems.append(f"hosts_lost={report.hosts_lost} misses crash-host")
    if report.units_stolen < 1:
        problems.append("no unit was stolen despite the host kill")
    if report.stalls_killed < 1:
        problems.append("no stall was killed despite the stall fault")
    if report.lanes_quarantined < 1:
        problems.append("no lane was quarantined despite the NaN fault")

    # Healthy lanes: bitwise-identical to the unfaulted oracle; poisoned
    # lanes: bitwise prefix before the injected epoch, zero-masked after.
    dividends = store.collect("dividends")
    entries = quarantine_entries(store)
    poisoned = {e.case: e.epoch for e in entries}
    for lane in range(dividends.shape[0]):
        if lane in poisoned:
            epoch = poisoned[lane]
            if not np.array_equal(
                dividends[lane][:epoch], oracle[lane][:epoch]
            ):
                problems.append(
                    f"poisoned lane {lane} prefix differs from oracle"
                )
            if not (dividends[lane][epoch:] == 0).all():
                problems.append(
                    f"poisoned lane {lane} not zero-masked from epoch "
                    f"{epoch}"
                )
        elif not np.array_equal(dividends[lane], oracle[lane]):
            problems.append(
                f"healthy lane {lane} is not bitwise-identical to the "
                "unfaulted run"
            )

    # The report reconciles with the merged ledgers, and every FINISHED
    # host's bundle is sound (ledger records resolve to spans). A
    # SIGKILLed host never runs its bundle-publish finally — its live
    # ledger IS its surviving record; demanding spans of the dead is
    # exactly the false positive the gate must not produce.
    problems.extend(check_fleet(store.directory))
    for host_id in report.hosts_finished:
        bundle = load_bundle(store.host_dir(host_id))
        problems.extend(
            f"host {host_id}: {p}" for p in check_bundle(bundle)
        )
    derived = build_fleet_report(store)
    if derived != report:
        problems.append("re-derived fleet report differs from published")

    # ONE stitched trace: the union of every host bundle (driver
    # included) must resolve — no orphan spans — and every span in
    # every FINISHED host's bundle must chain up to a root span of the
    # DRIVER's run (the env-propagated trace actually took).
    all_bundles = [
        load_bundle(store.host_dir(h)) for h in store.host_ids()
    ]
    problems.extend(check_stitched(all_bundles))
    union: dict = {}
    for b in all_bundles:
        for s in b.spans:
            union[s.get("span_id")] = s
    driver_span_ids = {
        s.get("span_id")
        for b in all_bundles
        if b.directory.name == DRIVER_HOST_ID
        for s in b.spans
    }
    def _chain_root(s: dict):
        cur = s
        for _ in range(len(union) + 1):
            parent = cur.get("parent_id", "")
            if not parent:
                return cur
            cur = union.get(parent)
            if cur is None:
                return None  # broken chain (check_stitched flagged it)
        return None  # cycle (check_stitched flagged it)

    for host_id in report.hosts_finished:
        for s in load_bundle(store.host_dir(host_id)).spans:
            if s.get("run_id") != driver_run.run_id:
                problems.append(
                    f"host {host_id} span {s.get('span_id')} minted run "
                    f"{s.get('run_id')} instead of continuing the "
                    f"driver's {driver_run.run_id}"
                )
                continue
            root = _chain_root(s)
            if root is not None and root.get("span_id") not in driver_span_ids:
                problems.append(
                    f"host {host_id} span {s.get('span_id')} roots at "
                    f"{root.get('span_id')}, not a driver span"
                )

    if problems:
        raise AssertionError(
            "fleet drill verification failed:\n"
            + "\n".join(f"  - {p}" for p in problems)
        )
    return {
        "store": store_dir,
        "report": report,
        "oracle": oracle,
        "dividends": dividends,
        "merged": merged,
        "rcs": results,
        "logs": streams,
    }


if __name__ == "__main__":
    raise SystemExit(main())
