"""The shared fleet store: manifest + content-addressed unit results.

One directory (any filesystem every host can see) holds a fleet sweep's
entire coordination state:

```
store/
  manifest.json            # write-once: unit map + config fingerprint
  leases/                  # one claim file per unit (.lease / stale_*)
  results/
    unit_00003.npz         # one published result per unit
    unit_00003.sha256      # its integrity sidecar
  hosts/<host_id>/         # each host's flight bundle (ledger.jsonl,
                           # spans.jsonl, metrics.jsonl)
  fleet_report.json        # the merged FleetHealthReport (finalize)
```

Multi-writer discipline: every mutable file has exactly ONE writer —
leases are per-unit (and claim-arbitrated, :mod:`.lease`), results are
per-unit (and lease-gated), host bundles are per-host, and the manifest
is write-once-validate-after (the `CheckpointedSweep` rule). There is
deliberately no shared checksums.json: per-unit sidecars mean two hosts
never contend on one JSON file.

At-most-once publish: :meth:`FleetStore.publish_result` refuses to
overwrite a result that verifies. Unit results are pure functions of the
manifest's config fingerprint and the unit's lane bounds — deterministic
and bitwise-reproducible (the `DispatchPlan` contract) — so duplicate
EXECUTION (a stolen unit whose original holder was mid-compute) is
harmless by construction, and duplicate PUBLISH is suppressed here: the
second publisher sees a verified result and records a duplicate instead.
A result that exists but FAILS verification (torn write, bit rot) is
overwritten — corruption requeues, exactly as checkpoint chunks do.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import pathlib
import uuid
from typing import Optional

import numpy as np

from yuma_simulation_tpu.utils.checkpoint import (
    _fsync_dir,
    _fsync_write,
    publish_atomic,
)

logger = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.json"
LEASES_DIR = "leases"
RESULTS_DIR = "results"
HOSTS_DIR = "hosts"
FLEET_REPORT_NAME = "fleet_report.json"


def is_fleet_store(directory: str | pathlib.Path) -> bool:
    """Whether `directory` is a fleet store (vs a plain supervised-sweep
    checkpoint directory): its manifest carries the fleet unit map."""
    manifest = pathlib.Path(directory) / MANIFEST_NAME
    if not manifest.exists():
        return False
    try:
        data = json.loads(manifest.read_text())
    except (json.JSONDecodeError, OSError):
        return False
    return isinstance(data, dict) and "unit_lanes" in data


def _file_sha256(path: pathlib.Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


class FleetStore:
    """Handle on one fleet store directory (see the module docstring)."""

    def __init__(self, directory: str | pathlib.Path):
        self.directory = pathlib.Path(directory)
        self.results_dir = self.directory / RESULTS_DIR
        self.leases_dir = self.directory / LEASES_DIR
        self.hosts_dir = self.directory / HOSTS_DIR
        for d in (self.directory, self.results_dir, self.leases_dir,
                  self.hosts_dir):
            d.mkdir(parents=True, exist_ok=True)

    # -- manifest -------------------------------------------------------

    def ensure_manifest(
        self,
        *,
        num_units: Optional[int] = None,
        unit_lanes=None,
        tag: str = "",
        config=None,
        trace: Optional[dict] = None,
    ) -> dict:
        """Write the manifest once, validate it ever after (the
        `CheckpointedSweep` rule: a store directory must never silently
        mix sweeps). Every host of a fleet calls this with identical
        arguments; the first to arrive writes, the rest verify. Two
        hosts racing the first write publish byte-identical content, so
        the race is harmless.

        `trace` (a :meth:`..telemetry.propagation.TraceContext
        .to_manifest` dict) rides the manifest so every joining host
        continues the SWEEP-LEVEL trace instead of minting an orphan
        run. It is deliberately EXCLUDED from the identity check: the
        trace names who drove the sweep, not what the sweep is — the
        first writer's trace wins, and hosts arriving with a different
        (or no) ambient trace still join."""
        path = self.directory / MANIFEST_NAME
        meta = None
        if num_units is not None:
            try:
                fingerprint = json.dumps(config, sort_keys=True)
            except TypeError as e:
                raise TypeError(
                    "fleet config must be JSON-serializable "
                    f"(got {type(config).__name__}): {e}"
                ) from e
            meta = {
                "fleet": tag or "fleet",
                "num_units": int(num_units),
                "unit_lanes": [
                    [int(lo), int(hi)] for lo, hi in (unit_lanes or ())
                ],
                "config_fingerprint": hashlib.sha256(
                    fingerprint.encode()
                ).hexdigest(),
            }
            if len(meta["unit_lanes"]) != meta["num_units"]:
                raise ValueError(
                    "unit_lanes must carry one [lo, hi] pair per unit"
                )
            if trace:
                meta["trace"] = dict(trace)
        def _verify(found: dict) -> dict:
            if meta is not None:
                mismatched = {
                    k: (found.get(k), v)
                    for k, v in meta.items()
                    if k != "trace" and found.get(k) != v
                }
                if mismatched:
                    raise ValueError(
                        f"fleet store {self.directory} holds a different "
                        f"sweep: {mismatched}"
                    )
            return found

        if path.exists():
            return _verify(json.loads(path.read_text()))
        if meta is None:
            raise FileNotFoundError(
                f"fleet store {self.directory} has no manifest and none "
                "was provided (num_units/unit_lanes)"
            )
        # Exactly-one-winner first write (the lease-claim idiom): two
        # hosts racing here may carry DIFFERENT traces, so last-rename-
        # wins would let the loser proceed on a trace the manifest does
        # not record. The hard link makes the first writer's manifest
        # authoritative; the loser verifies and joins it.
        staged = path.with_name(f".{MANIFEST_NAME}.{uuid.uuid4().hex}.stage")
        publish_atomic(staged, json.dumps(meta, sort_keys=True).encode())
        try:
            os.link(staged, path)
        except FileExistsError:
            return _verify(json.loads(path.read_text()))
        finally:
            staged.unlink(missing_ok=True)
        _fsync_dir(path.parent)
        return meta

    def manifest(self) -> dict:
        return self.ensure_manifest()

    # -- results --------------------------------------------------------

    def result_path(self, unit: int) -> pathlib.Path:
        return self.results_dir / f"unit_{unit:05d}.npz"

    def _sidecar_path(self, unit: int) -> pathlib.Path:
        return self.results_dir / f"unit_{unit:05d}.sha256"

    def verify_result(self, unit: int) -> bool:
        """Published and intact: sha256 against the per-unit sidecar
        (no sidecar -> decode probe, the legacy-chunk rule)."""
        path = self.result_path(unit)
        if not path.exists():
            return False
        sidecar = self._sidecar_path(unit)
        if sidecar.exists():
            try:
                recorded = json.loads(sidecar.read_text())["sha256"]
            except (json.JSONDecodeError, OSError, KeyError):
                recorded = None
            if recorded is not None:
                return _file_sha256(path) == recorded
        try:
            with np.load(path, allow_pickle=False) as z:
                list(z.keys())
            return True
        except Exception:
            return False

    def publish_result(self, unit: int, arrays: dict) -> bool:
        """Publish `unit`'s result atomically (npz + sha256 sidecar,
        both fsync'd, parent directory fsync'd). Returns False — and
        writes nothing — when a verified result already exists (the
        at-most-once publish gate); an unverifiable existing result is
        overwritten (corruption requeues)."""
        if self.verify_result(unit):
            return False
        payload = {k: np.asarray(v) for k, v in arrays.items()}
        # Writer-unique temp: in the (deterministic-content) race where
        # two executions publish the same unit, neither may truncate the
        # other's in-flight bytes — each rename lands whole.
        tmp = self.results_dir / (
            f".partial_{unit:05d}.{uuid.uuid4().hex[:8]}.tmp"
        )
        buf = io.BytesIO()
        np.savez(buf, **payload)
        data = buf.getvalue()
        _fsync_write(tmp, lambda f: f.write(data))
        digest = _file_sha256(tmp)
        tmp.replace(self.result_path(unit))
        _fsync_dir(self.results_dir)
        publish_atomic(
            self._sidecar_path(unit),
            json.dumps({"sha256": digest}, sort_keys=True).encode(),
        )
        return True

    def load_result(self, unit: int) -> Optional[dict]:
        """Decode `unit`'s published arrays, or None when missing or
        undecodable (the caller requeues)."""
        try:
            with np.load(self.result_path(unit), allow_pickle=False) as z:
                return {k: np.asarray(z[k]) for k in z.keys()}
        except Exception:
            return None

    def published_units(self) -> list[int]:
        done = []
        for p in self.results_dir.glob("unit_*.npz"):
            tail = p.stem.split("_", 1)[1]
            if tail.isdigit():
                done.append(int(tail))
        return sorted(done)

    def pending_units(self, *, deep: bool = True) -> list[int]:
        """Units without a VERIFIED result (a published-but-corrupt
        result counts as pending: corruption requeues). `deep=False` is
        the scheduler's hot-loop variant: existence of the result and
        its sidecar only — no hashing, so an idle host polling a large
        store costs stats, not a re-read of every published byte. The
        scheduler re-runs the deep scan as its completion barrier (and
        fully verifies at claim and collect time), so a corrupt result
        is still caught and requeued."""
        n = self.manifest()["num_units"]
        if deep:
            return [u for u in range(n) if not self.verify_result(u)]
        return [
            u
            for u in range(n)
            if not (
                self.result_path(u).exists()
                and self._sidecar_path(u).exists()
            )
        ]

    def collect(self, key: str = "dividends") -> np.ndarray:
        """Concatenate every unit's `key` array in unit order. Raises
        when any unit is missing or fails verification — a fleet result
        is complete or it is not a result."""
        n = self.manifest()["num_units"]
        parts = []
        for unit in range(n):
            if not self.verify_result(unit):
                raise FileNotFoundError(
                    f"fleet store {self.directory} has no verified result "
                    f"for unit {unit}"
                )
            loaded = self.load_result(unit)
            if loaded is None or key not in loaded:
                raise KeyError(
                    f"unit {unit} result in {self.directory} carries no "
                    f"{key!r} array"
                )
            parts.append(loaded[key])
        return np.concatenate(parts, axis=0)

    # -- host bundles ---------------------------------------------------

    def host_dir(self, host_id: str) -> pathlib.Path:
        d = self.hosts_dir / host_id
        d.mkdir(parents=True, exist_ok=True)
        return d

    def host_ids(self) -> list[str]:
        return sorted(
            p.name for p in self.hosts_dir.iterdir() if p.is_dir()
        )
