"""Incremental epoch-state prefix caching for what-if suffix resume.

The consensus state at epoch ``k`` is a small pytree (``bonds [V, M]``,
``consensus [M]``, sometimes ``w_prev [V, M]``) — a few MB even at the
real-subnet flagship shape, against the ``[E, V, M]`` epoch stack a
full re-simulation re-pays. This module checkpoints a baseline
trajectory's carry every ``stride`` epochs through the engine's
suffix-resume contract (``simulate(..., initial_state=, epoch_offset=,
return_state=True)`` — :mod:`..simulation.engine`), so a what-if that
perturbs epoch ``k`` re-simulates only epochs ``[k', E)`` from the
nearest checkpoint ``k' <= k`` — turning a 40-epoch request into a
~5-epoch one, **bitwise identical** to the uncached run (the segment
boundaries ride the same carry-threading contract chunked streaming is
pinned on).

On-disk layout under one cache root (every write
:func:`..utils.checkpoint.publish_atomic` — crash leaves old or new,
never torn)::

    <root>/
      lru.json                     # access sequence per baseline key
      <baseline-key>/              # sha256 of what determines the bits
        meta.json                  # shape/version/engine/stride/checkpoints
        baseline.npz               # dividends [E, V] (+ incentives [E, M])
        state_<epoch>.npz          # serialized carry at each checkpoint

The baseline key is content-addressed over everything that determines
the trajectory's bits — the timeline/scenario fingerprint, version,
config, dtype, epoch count, checkpoint stride, and the PINNED engine
rung (baseline and suffix must run the same rung, or "bitwise" would
silently mean "to reduction-order rounding"). The store is LRU-bounded:
`max_baselines` trajectories, least-recently-used evicted whole.

Telemetry: every resolve is a typed ``state_cache_hit`` /
``state_cache_miss`` event plus the matching counter, and every hit
adds the epochs it skipped to ``replay_suffix_epochs_saved`` — the
series ``tools/obsreport.py``'s replay section renders.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import logging
import pathlib
import threading
from typing import Optional, Union

import numpy as np

from yuma_simulation_tpu.utils.checkpoint import publish_atomic
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)


class StateCacheError(ValueError):
    """A cache operation that violates the store contract (unknown
    baseline, corrupt artifact, inconsistent meta)."""


def config_fingerprint(config) -> str:
    """Canonical content address of a YumaConfig: every float/bool leaf
    in sorted field order. Two configs with equal leaves fingerprint
    equal regardless of construction path."""
    flat = {}

    def walk(prefix: str, obj) -> None:
        if dataclasses.is_dataclass(obj):
            for f in dataclasses.fields(obj):
                walk(f"{prefix}{f.name}.", getattr(obj, f.name))
        elif obj is None or isinstance(obj, (bool, int, float, str)):
            flat[prefix.rstrip(".")] = obj
        else:
            flat[prefix.rstrip(".")] = repr(obj)

    walk("", config)
    payload = json.dumps(flat, sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()


def baseline_key(
    *,
    scenario_fingerprint: str,
    version: str,
    config,
    dtype: str,
    epochs: int,
    stride: int,
    engine: str,
) -> str:
    """The content address one cached baseline lives under (module
    docstring: everything that determines the trajectory's bits)."""
    payload = json.dumps(
        {
            "scenario": scenario_fingerprint,
            "version": version,
            "config": config_fingerprint(config),
            "dtype": str(dtype),
            "epochs": int(epochs),
            "stride": int(stride),
            "engine": engine,
        },
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()


def serialize_state(state: dict) -> bytes:
    """One consensus carry as canonical npz bytes (the same dict
    :attr:`..simulation.engine.SimulationResult.final_state` holds)."""
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in sorted(state.items())})
    return buf.getvalue()


def deserialize_state(blob: bytes) -> dict:
    with np.load(io.BytesIO(blob)) as data:
        return {k: np.asarray(data[k]) for k in data.files}


@dataclasses.dataclass(frozen=True)
class BaselineMeta:
    """What a cached baseline is: enough to admit, price, and resume a
    what-if without touching the arrays."""

    key: str
    epochs: int
    validators: int
    miners: int
    version: str
    engine: str  # the PINNED rung every segment and suffix runs on
    stride: int
    dtype: str
    checkpoints: tuple  # ascending checkpoint epochs (stride, 2*stride, ..)
    scenario_fingerprint: str
    scenario_name: str = ""

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["checkpoints"] = list(self.checkpoints)
        return d

    @classmethod
    def from_json(cls, payload: dict) -> "BaselineMeta":
        try:
            return cls(
                **{
                    **payload,
                    "checkpoints": tuple(
                        int(c) for c in payload["checkpoints"]
                    ),
                }
            )
        except (KeyError, TypeError) as exc:
            raise StateCacheError(f"corrupt baseline meta: {exc}") from None


class StateCache:
    """The LRU-bounded, content-addressed baseline/carry store."""

    def __init__(
        self,
        root: Union[str, pathlib.Path],
        *,
        max_baselines: int = 64,
    ):
        if max_baselines < 1:
            raise ValueError(
                f"max_baselines must be >= 1, got {max_baselines}"
            )
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.max_baselines = max_baselines
        # Serializes LRU read-modify-write and eviction against
        # concurrent store/touch from handler threads (jaxlint JX101:
        # the guarded state is only ever touched under the lock).
        self._lock = threading.Lock()
        from yuma_simulation_tpu.telemetry.metrics import get_registry

        registry = get_registry()
        self._hits = registry.counter(
            "state_cache_hits",
            help="what-if suffix resumes served from a cached epoch state",
        )
        self._misses = registry.counter(
            "state_cache_misses",
            help="what-if requests with no usable cached epoch state",
        )
        self._epochs_saved = registry.counter(
            "replay_suffix_epochs_saved",
            help="epochs a cached carry let what-ifs skip re-simulating",
        )

    # -- layout ---------------------------------------------------------

    def _dir(self, key: str) -> pathlib.Path:
        return self.root / key

    def _meta_path(self, key: str) -> pathlib.Path:
        return self._dir(key) / "meta.json"

    def _state_path(self, key: str, epoch: int) -> pathlib.Path:
        return self._dir(key) / f"state_{int(epoch):06d}.npz"

    def _baseline_path(self, key: str) -> pathlib.Path:
        return self._dir(key) / "baseline.npz"

    # -- LRU ------------------------------------------------------------

    def _touch_locked(self, key: str) -> None:
        path = self.root / "lru.json"
        try:
            lru = json.loads(path.read_text()) if path.exists() else {}
        except json.JSONDecodeError:
            lru = {}
        lru[key] = max((int(v) for v in lru.values()), default=0) + 1
        publish_atomic(path, json.dumps(lru, sort_keys=True).encode())

    def _evict_locked(self) -> None:
        import shutil

        path = self.root / "lru.json"
        try:
            lru = json.loads(path.read_text()) if path.exists() else {}
        except json.JSONDecodeError:
            lru = {}
        keys = [
            p.name for p in self.root.iterdir()
            if p.is_dir() and (p / "meta.json").exists()
        ]
        if len(keys) <= self.max_baselines:
            return
        keys.sort(key=lambda k: int(lru.get(k, 0)))
        for stale in keys[: len(keys) - self.max_baselines]:
            shutil.rmtree(self._dir(stale), ignore_errors=True)
            lru.pop(stale, None)
            logger.info("state cache evicted baseline %s", stale[:16])
        publish_atomic(path, json.dumps(lru, sort_keys=True).encode())

    # -- reads ----------------------------------------------------------

    def keys(self) -> list[str]:
        return sorted(
            p.name for p in self.root.iterdir()
            if p.is_dir() and (p / "meta.json").exists()
        )

    def meta(self, key: str) -> Optional[BaselineMeta]:
        path = self._meta_path(key)
        if not path.exists():
            return None
        try:
            return BaselineMeta.from_json(json.loads(path.read_text()))
        except (json.JSONDecodeError, StateCacheError):
            logger.warning("dropping corrupt baseline meta %s", key[:16])
            return None

    def held_prefixes(self) -> list[dict]:
        """Everything this cache can resume FROM, as an advertisement:
        one record per held baseline — the content-addressed key, the
        largest checkpoint epoch whose state file is actually readable
        on disk (the suffix-savings currency), and the identity fields
        a router needs to match a what-if to the baseline WITHOUT
        recomputing the key (scenario fingerprint/name, version,
        engine, total epochs). The serve scale-out tier publishes this
        in each worker's heartbeat so state-cache-affinity routing can
        score claims by suffix-epochs-saved (serve/router.py)."""
        ads = []
        for key in self.keys():
            meta = self.meta(key)
            if meta is None:
                continue
            held = [
                c
                for c in meta.checkpoints
                if self._state_path(key, c).exists()
            ]
            if not held:
                continue
            ads.append(
                {
                    "key": key,
                    "max_checkpoint": max(held),
                    "checkpoints": sorted(int(c) for c in held),
                    "epochs": meta.epochs,
                    "version": meta.version,
                    "engine": meta.engine,
                    "scenario_fingerprint": meta.scenario_fingerprint,
                    "scenario_name": meta.scenario_name,
                }
            )
        return ads

    def resume_epoch(self, key: str, perturb_epoch: int) -> int:
        """The largest stored checkpoint epoch ``<= perturb_epoch`` —
        0 when none qualifies (resume from the zero state)."""
        meta = self.meta(key)
        if meta is None:
            return 0
        usable = [
            c
            for c in meta.checkpoints
            if c <= perturb_epoch and self._state_path(key, c).exists()
        ]
        return max(usable, default=0)

    def load_state(self, key: str, epoch: int) -> dict:
        path = self._state_path(key, epoch)
        try:
            return deserialize_state(path.read_bytes())
        except (OSError, ValueError, KeyError) as exc:
            raise StateCacheError(
                f"baseline {key[:16]}: state at epoch {epoch} unreadable "
                f"({exc})"
            ) from None

    def load_baseline(self, key: str) -> dict:
        """The baseline trajectory's outputs:
        ``{"dividends" [E, V], "incentives" [E, M]}``."""
        path = self._baseline_path(key)
        try:
            with np.load(path) as data:
                return {k: np.asarray(data[k]) for k in data.files}
        except (OSError, ValueError, KeyError) as exc:
            raise StateCacheError(
                f"baseline {key[:16]}: trajectory unreadable ({exc})"
            ) from None

    # -- telemetry ------------------------------------------------------

    def record_hit(
        self, key: str, *, resume_epoch: int, total_epochs: int
    ) -> None:
        self._hits.inc()
        self._epochs_saved.inc(resume_epoch)
        log_event(
            logger,
            "state_cache_hit",
            level=logging.INFO,
            baseline=key[:16],
            resume_epoch=resume_epoch,
            suffix_epochs=total_epochs - resume_epoch,
            epochs_saved=resume_epoch,
        )

    def record_miss(self, key: str, *, total_epochs: int, reason: str) -> None:
        self._misses.inc()
        log_event(
            logger,
            "state_cache_miss",
            level=logging.INFO,
            baseline=key[:16],
            full_epochs=total_epochs,
            reason=reason,
        )

    # -- build ----------------------------------------------------------

    def build_baseline(
        self,
        scenario,
        version: str,
        config=None,
        *,
        scenario_fingerprint: str,
        stride: int = 8,
        engine: str = "auto",
        dtype=None,
    ) -> BaselineMeta:
        """Simulate one baseline trajectory in ``stride``-epoch segments
        through the suffix-resume engine contract, checkpointing the
        carry at every segment boundary, and publish trajectory +
        states + meta under the content-addressed key. Segment runs are
        bitwise the monolithic trajectory (the carry-threading
        contract), so any suffix resumed from any checkpoint continues
        the exact bits a full run would have produced.

        An already-published identical baseline is reused (the key IS
        the content), making rebuilds idempotent and cheap."""
        import dataclasses as dc

        import jax.numpy as jnp

        from yuma_simulation_tpu.models.config import YumaConfig
        from yuma_simulation_tpu.simulation.engine import simulate
        from yuma_simulation_tpu.simulation.planner import plan_dispatch

        config = config if config is not None else YumaConfig()
        dtype = dtype if dtype is not None else jnp.float32
        if stride < 1:
            raise ValueError(f"stride must be >= 1, got {stride}")
        E, V, M = np.shape(scenario.weights)
        if engine == "auto":
            # Pin the rung ONCE for the baseline's whole lifetime: every
            # segment and every later suffix must run the same engine,
            # or bitwise equality degrades to reduction-order rounding.
            engine = plan_dispatch(
                f"replay:baseline:{version}",
                (E, V, M),
                version,
                config,
                dtype,
            ).engine
            if engine in ("fused_varying_mxu", "fused_varying"):
                # The epoch-tiled rungs are bitwise-reproducible only
                # between runs sharing one program (one tile) — but the
                # cache's whole point is composing stride segments,
                # suffixes and full runs of DIFFERENT epoch counts,
                # which pick different divisor tiles. Pin the per-epoch
                # case-scan twin instead: same kernel family and speed
                # class, and cross-epoch-count composition stays
                # bitwise (the suffix-resume property pins).
                engine = (
                    "fused_scan_mxu"
                    if engine == "fused_varying_mxu"
                    else "fused_scan"
                )
        key = baseline_key(
            scenario_fingerprint=scenario_fingerprint,
            version=version,
            config=config,
            dtype=jnp.dtype(dtype).name,
            epochs=E,
            stride=stride,
            engine=engine,
        )
        existing = self.meta(key)
        if existing is not None:
            with self._lock:
                self._touch_locked(key)
            return existing

        carry = None
        dividends, incentives = [], []
        states: dict[int, dict] = {}
        for lo in range(0, E, stride):
            hi = min(lo + stride, E)
            segment = dc.replace(
                scenario,
                weights=scenario.weights[lo:hi],
                stakes=scenario.stakes[lo:hi],
                num_epochs=hi - lo,
            )
            result = simulate(
                segment,
                version,
                config,
                save_bonds=False,
                save_incentives=True,
                epoch_impl=engine,
                dtype=dtype,
                initial_state=carry,
                epoch_offset=lo,
                return_state=True,
            )
            dividends.append(result.dividends)
            incentives.append(result.incentives)
            carry = result.final_state
            # Interior boundaries feed what-if suffix resume (meta
            # .checkpoints); the FINAL carry at E additionally publishes
            # as a state file so the continuous-replay controller can
            # extend this baseline incrementally (`extend_baseline`)
            # without re-simulating the prefix. It is deliberately NOT
            # listed in meta.checkpoints — a what-if never resumes past
            # its perturbation epoch, and existing consumers pin the
            # interior-only tuple.
            states[hi] = carry
        target = self._dir(key)
        target.mkdir(parents=True, exist_ok=True)
        for epoch, state in states.items():
            publish_atomic(
                self._state_path(key, epoch), serialize_state(state)
            )
        buf = io.BytesIO()
        np.savez(
            buf,
            dividends=np.concatenate(dividends),
            incentives=np.concatenate(incentives),
        )
        publish_atomic(self._baseline_path(key), buf.getvalue())
        meta = BaselineMeta(
            key=key,
            epochs=E,
            validators=V,
            miners=M,
            version=version,
            engine=engine,
            stride=stride,
            dtype=jnp.dtype(dtype).name,
            checkpoints=tuple(sorted(c for c in states if c < E)),
            scenario_fingerprint=scenario_fingerprint,
            scenario_name=scenario.name,
        )
        # Meta LAST: its presence is what marks the baseline published
        # (readers treat a directory without meta.json as absent).
        publish_atomic(
            self._meta_path(key),
            json.dumps(meta.to_json(), sort_keys=True).encode(),
        )
        with self._lock:
            self._touch_locked(key)
            self._evict_locked()
        return meta

    def final_state(self, key: str) -> dict:
        """The carry AFTER a baseline's last epoch (the extension
        point `extend_baseline` resumes from). Typed
        :class:`StateCacheError` when the baseline or its final state
        file is absent — pre-0.22.0 baselines never published one, and
        the caller's fallback is a full rebuild."""
        meta = self.meta(key)
        if meta is None:
            raise StateCacheError(f"no baseline {key[:16]} to extend")
        return self.load_state(key, meta.epochs)

    def extend_baseline(
        self,
        prior_key: str,
        suffix_scenario,
        *,
        scenario_fingerprint: str,
        config=None,
    ) -> BaselineMeta:
        """Extend a published baseline by `suffix_scenario`'s epochs
        through the suffix-resume contract: resume from the prior
        baseline's final carry, simulate ONLY the new epochs (stride
        segments aligned to the prior baseline's global checkpoint
        grid), and publish the concatenated trajectory under the NEW
        content-addressed key — the continuous-replay controller's
        incremental refresh, bitwise identical to a from-scratch
        :meth:`build_baseline` of the full extended window (same
        engine, same stride, same carry-threading contract), at the
        cost of the suffix alone.

        Idempotent exactly like :meth:`build_baseline` (the key IS the
        content). Typed :class:`StateCacheError` when the prior
        baseline, its final state, or its trajectory is unreadable —
        the caller's fallback is a full rebuild."""
        import dataclasses as dc

        import jax.numpy as jnp

        from yuma_simulation_tpu.models.config import YumaConfig
        from yuma_simulation_tpu.simulation.engine import simulate

        prior = self.meta(prior_key)
        if prior is None:
            raise StateCacheError(f"no baseline {prior_key[:16]} to extend")
        config = config if config is not None else YumaConfig()
        E0 = prior.epochs
        E_suffix, V, M = np.shape(suffix_scenario.weights)
        if (V, M) != (prior.validators, prior.miners):
            raise StateCacheError(
                f"baseline {prior_key[:16]} is [{prior.validators}, "
                f"{prior.miners}] but the suffix is [{V}, {M}] — a "
                "re-shaped subnet starts a new baseline"
            )
        E1 = E0 + E_suffix
        stride = prior.stride
        key = baseline_key(
            scenario_fingerprint=scenario_fingerprint,
            version=prior.version,
            config=config,
            dtype=prior.dtype,
            epochs=E1,
            stride=stride,
            engine=prior.engine,
        )
        existing = self.meta(key)
        if existing is not None:
            with self._lock:
                self._touch_locked(key)
            return existing

        carry = self.final_state(prior_key)
        trajectory = self.load_baseline(prior_key)
        # Segment bounds continue the GLOBAL stride grid (0, stride,
        # 2*stride, ...), so the extended baseline's checkpoint set is
        # exactly what a from-scratch build would have published.
        bounds = [E0]
        nxt = (E0 // stride + 1) * stride
        while nxt < E1:
            bounds.append(nxt)
            nxt += stride
        bounds.append(E1)
        dividends = [trajectory["dividends"]]
        incentives = [trajectory["incentives"]]
        states: dict[int, dict] = {}
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            segment = dc.replace(
                suffix_scenario,
                weights=suffix_scenario.weights[lo - E0 : hi - E0],
                stakes=suffix_scenario.stakes[lo - E0 : hi - E0],
                num_epochs=hi - lo,
            )
            result = simulate(
                segment,
                prior.version,
                config,
                save_bonds=False,
                save_incentives=True,
                epoch_impl=prior.engine,
                dtype=jnp.dtype(prior.dtype),
                initial_state=carry,
                epoch_offset=lo,
                return_state=True,
            )
            dividends.append(result.dividends)
            incentives.append(result.incentives)
            carry = result.final_state
            states[hi] = carry
        target = self._dir(key)
        target.mkdir(parents=True, exist_ok=True)
        # The prior baseline's checkpoints carry over (byte copy — the
        # carries are the same trajectory's), plus the prior FINAL
        # state when it lands on the stride grid.
        inherited = [c for c in prior.checkpoints]
        if E0 % stride == 0:
            inherited.append(E0)
        for epoch in inherited:
            try:
                blob = self._state_path(prior_key, epoch).read_bytes()
            except OSError:
                continue  # a missing inherited checkpoint narrows resume
            publish_atomic(self._state_path(key, epoch), blob)
        for epoch, state in states.items():
            publish_atomic(
                self._state_path(key, epoch), serialize_state(state)
            )
        buf = io.BytesIO()
        np.savez(
            buf,
            dividends=np.concatenate(dividends),
            incentives=np.concatenate(incentives),
        )
        publish_atomic(self._baseline_path(key), buf.getvalue())
        checkpoints = sorted(
            set(c for c in inherited if c < E1)
            | set(c for c in states if c < E1)
        )
        meta = BaselineMeta(
            key=key,
            epochs=E1,
            validators=V,
            miners=M,
            version=prior.version,
            engine=prior.engine,
            stride=stride,
            dtype=prior.dtype,
            checkpoints=tuple(checkpoints),
            scenario_fingerprint=scenario_fingerprint,
            scenario_name=suffix_scenario.name,
        )
        publish_atomic(
            self._meta_path(key),
            json.dumps(meta.to_json(), sort_keys=True).encode(),
        )
        with self._lock:
            self._touch_locked(key)
            self._evict_locked()
        return meta
