"""The continuous multi-subnet replay controller (ROADMAP item 5's
standing half): watch N archive timelines, sweep ONLY the suffix past a
durable watermark, and self-heal through crashes, corrupt blobs, and
stalled feeds.

The one-shot :mod:`.sweeper` re-simulates every (subnet x variant)
window from scratch each time it runs. This module is its standing
replacement for archives that KEEP APPENDING:

- **Watermarks** (:class:`WatermarkStore`) — one durable JSONL per
  (subnet x variant) recording the last swept block, the cumulative
  epoch count, and the cache baseline that holds the carry at that
  point. Appends republish the whole file through
  :func:`..utils.checkpoint.publish_atomic` (the
  :class:`..resilience.supervisor.FailureLedger` discipline), and loads
  tolerate a torn tail, so a SIGKILL at any instant leaves a parseable
  history whose newest valid record IS the resume point.
- **Incremental windows** — each cycle compiles the entries past the
  watermark into one scenario (:meth:`..replay.archive.SnapshotArchive
  .scenario_for_blocks`) and runs it as a lease-claimed
  :func:`..fabric.scheduler.run_fleet_grid` unit resumed from the
  cached carry (``initial_state=`` / ``epoch_offset=`` — the engine's
  suffix-resume contract), so an incremental window's dividends are
  BITWISE the corresponding rows of a full from-genesis re-simulation
  (cross-checked against the extended cache baseline on every sweep).
- **Exactly-once publication** — the window's fleet store path is
  derived from its block span and the window membership is pinned
  durably (``inflight.json``) BEFORE dispatch, so a controller killed
  between fleet publish and watermark advance resumes the SAME window:
  already-published units are satisfied instantly by the store's
  at-most-once publish gate and only genuinely in-flight work
  re-simulates. The watermark advances strictly AFTER publish +
  baseline extension — at-least-once sweep, exactly-once publication.
- **Quarantine** (corrupt blobs) — a snapshot whose blob fails its
  content-address check raises the archive's typed
  :class:`..replay.archive.ArchiveError`; the controller records a
  durable ``subnet_quarantined`` ledger entry, excludes the block from
  every future window (the window fingerprint covers exactly the
  entries compiled), and keeps the subnet draining.
- **Stall demotion** — a subnet whose head block stops moving past
  ``stall_deadline_seconds`` emits one typed ``subnet_stalled`` record
  and drops to the slow poll tier until it appends again.
- **Freshness SLO + backpressure** — per cycle, each live subnet feeds
  one good/bad verdict into the ``replay_freshness`` objective
  (:data:`..telemetry.slo.DEFAULT_SLO_SPECS`; ``replay_staleness_
  seconds`` is the gauge twin), and ``max_windows_per_cycle`` sheds the
  lowest-priority refreshes first when the backlog exceeds the budget.

Helper fleet hosts (:func:`run_host`, ``python -m
yuma_simulation_tpu.replay --host``) scan the pair directories for
in-flight window specs, reconstruct the identical scenario + carry from
the shared archive/cache, and join the fleet store through the ordinary
lease-claim path — the manifest's carry digest rejects a host holding a
stale resume point instead of letting it publish different bits.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import pathlib
import time
from typing import Callable, Optional, Sequence, Union

import numpy as np

from yuma_simulation_tpu.replay.archive import (
    ArchiveError,
    SnapshotArchive,
    entries_fingerprint,
)
from yuma_simulation_tpu.replay.statecache import StateCache, StateCacheError
from yuma_simulation_tpu.replay.sweeper import version_slug
from yuma_simulation_tpu.utils.checkpoint import (
    publish_atomic,
    read_jsonl_tolerant,
)
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)


class ControllerError(RuntimeError):
    """A continuous-replay invariant violation (non-monotone watermark
    advance, a fleet/cache bitwise mismatch)."""


# ---------------------------------------------------------- watermarks


class WatermarkStore:
    """Durable per-(subnet x variant) sweep watermarks.

    Layout: ``<root>/subnet_<netuid>/<version-slug>.jsonl``, one JSON
    record per advance (append-ordered). Every append republishes the
    whole file atomically; loads skip torn/corrupt lines and take the
    highest-block valid record, so partial writes from a killed
    controller can delay progress by one window but never corrupt or
    regress it."""

    def __init__(self, root: Union[str, pathlib.Path]):
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def path(self, netuid: int, version: str) -> pathlib.Path:
        return (
            self.root
            / f"subnet_{int(netuid)}"
            / f"{version_slug(version)}.jsonl"
        )

    def history(self, netuid: int, version: str) -> list[dict]:
        """All valid records, append order (torn lines skipped)."""
        return read_jsonl_tolerant(self.path(netuid, version))

    def load(self, netuid: int, version: str) -> Optional[dict]:
        """The current watermark: the highest-block valid record, or
        None when the pair has never been swept."""
        records = [
            r
            for r in self.history(netuid, version)
            if isinstance(r.get("block"), int)
        ]
        if not records:
            return None
        return max(records, key=lambda r: r["block"])

    def advance(
        self,
        netuid: int,
        version: str,
        *,
        block: int,
        epochs: int,
        baseline_key: str,
        window_store: str = "",
    ) -> dict:
        """Append one advance record (strictly monotone in block) and
        republish the file atomically. The caller MUST have published
        the window's fleet results and extended the cache baseline
        first — this record is the commit point that makes them
        visible to resume."""
        current = self.load(netuid, version)
        if current is not None and int(block) <= current["block"]:
            raise ControllerError(
                f"watermark subnet={netuid} {version!r} cannot advance "
                f"{current['block']} -> {block} (must be monotone)"
            )
        record = {
            "netuid": int(netuid),
            "version": version,
            "block": int(block),
            "epochs": int(epochs),
            "baseline_key": baseline_key,
            "window_store": window_store,
            "t": round(time.time(), 6),
        }
        records = self.history(netuid, version) + [record]
        payload = "".join(
            json.dumps(r, sort_keys=True) + "\n" for r in records
        )
        path = self.path(netuid, version)
        path.parent.mkdir(parents=True, exist_ok=True)
        publish_atomic(path, payload.encode())
        return record


# ------------------------------------------------------- window specs


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    """One in-flight incremental window, pinned durably BEFORE
    dispatch: enough for a crashed controller to resume the identical
    window (same blocks -> same store -> same at-most-once units) and
    for a helper fleet host to reconstruct the identical scenario and
    carry from the shared archive/cache."""

    netuid: int
    version: str
    #: the blocks this window compiles (quarantine already applied).
    blocks: tuple
    epochs_per_snapshot: int
    #: epochs already swept — the suffix's global epoch offset.
    epoch_offset: int
    #: cache baseline holding the carry at `epoch_offset` ("" = full
    #: from-scratch window, no resume).
    prior_baseline_key: str
    #: watermark block this window extends (None = never swept) — a
    #: resume only reuses the spec while the watermark still matches.
    base_block: Optional[int]
    #: full-window fingerprint (prefix + this window, quarantine
    #: filtered) the extended cache baseline is keyed on.
    scenario_fingerprint: str
    #: the window's fleet store directory.
    store: str

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["blocks"] = list(self.blocks)
        return d

    @classmethod
    def from_json(cls, payload: dict) -> "WindowSpec":
        try:
            return cls(
                netuid=int(payload["netuid"]),
                version=str(payload["version"]),
                blocks=tuple(int(b) for b in payload["blocks"]),
                epochs_per_snapshot=int(payload["epochs_per_snapshot"]),
                epoch_offset=int(payload["epoch_offset"]),
                prior_baseline_key=str(payload["prior_baseline_key"]),
                base_block=(
                    None
                    if payload.get("base_block") is None
                    else int(payload["base_block"])
                ),
                scenario_fingerprint=str(payload["scenario_fingerprint"]),
                store=str(payload["store"]),
            )
        except (KeyError, TypeError, ValueError) as exc:
            raise ControllerError(f"corrupt window spec: {exc}") from None


# ----------------------------------------------------------- config


@dataclasses.dataclass
class ControllerConfig:
    """The controller's knobs (defaults sized for the CPU soak)."""

    #: store root: per-pair fleet stores, watermarks, quarantine ledger.
    store_root: Union[str, pathlib.Path] = "replay-store"
    versions: Sequence[str] = ("Yuma 2 (Adrian-Fish)",)
    epochs_per_snapshot: int = 4
    #: carry-checkpoint stride of the cache baselines.
    stride: int = 8
    unit_size: int = 8
    canary_fraction: float = 1.0
    #: fast-tier poll period (live subnets).
    poll_seconds: float = 0.5
    #: slow-tier poll period (stalled subnets).
    slow_poll_seconds: float = 5.0
    #: head block unchanged this long -> subnet_stalled + slow tier.
    stall_deadline_seconds: float = 10.0
    #: staleness past this is a bad `replay_fresh` verdict.
    freshness_budget_seconds: float = 30.0
    #: windows swept per cycle before low-priority refreshes shed
    #: (None = unbounded).
    max_windows_per_cycle: Optional[int] = None
    #: netuid -> priority (higher sweeps first; missing = 0).
    priorities: dict = dataclasses.field(default_factory=dict)
    #: lease tuning forwarded to each window's FleetConfig.
    lease_ttl_seconds: float = 30.0
    max_wait_seconds: float = 600.0
    #: Yuma hyperparameters (None -> package defaults).
    config: object = None
    #: Continuous-telemetry rotation for the controller's flight bundle
    #: (``--rotate-flight``): ``True`` = default
    #: :class:`..telemetry.flight.RotationPolicy` bounds, a policy
    #: instance pins them, ``None`` (default) defers to the
    #: ``YUMA_TPU_FLIGHT_ROTATE`` env opt-in — rotation stays OFF
    #: unless explicitly requested.
    flight_rotation: object = None
    #: On-demand profiling (``--profile-window``): > 0 arms ONE guarded
    #: ``jax.profiler`` window of this many seconds over the first
    #: cycle that sweeps work, registered into the bundle's
    #: ``profiles.jsonl``. 0 disables (the default).
    profile_window_seconds: float = 0.0


@dataclasses.dataclass
class CycleReport:
    """What one poll cycle did (returned by :meth:`ReplayController
    .run_cycle`, aggregated by the soak)."""

    subnets_seen: int = 0
    subnets_live: int = 0
    subnets_stalled: int = 0
    windows_swept: int = 0
    windows_shed: int = 0
    snapshots_quarantined: int = 0
    max_staleness_seconds: float = 0.0
    #: (netuid, version, block_from, block_to) per swept window.
    swept: list = dataclasses.field(default_factory=list)


# -------------------------------------------------------- controller


class ReplayController:
    """The standing sweep loop (module docstring). One instance owns
    one store root; restarts are crash-safe by construction — all
    progress state (watermarks, quarantine, in-flight windows, fleet
    units) is durable, everything in memory is a rebuildable view."""

    def __init__(
        self,
        archive: SnapshotArchive,
        cache: StateCache,
        cfg: ControllerConfig,
        *,
        bundle_dir: Optional[Union[str, pathlib.Path]] = None,
    ):
        from yuma_simulation_tpu.resilience.supervisor import FailureLedger
        from yuma_simulation_tpu.telemetry.flight import FlightRecorder
        from yuma_simulation_tpu.telemetry.metrics import get_registry
        from yuma_simulation_tpu.telemetry.runctx import RunContext

        self.archive = archive
        self.cache = cache
        self.cfg = cfg
        self.store_root = pathlib.Path(cfg.store_root)
        self.store_root.mkdir(parents=True, exist_ok=True)
        self.watermarks = WatermarkStore(self.store_root / "watermarks")
        self.bundle_dir = pathlib.Path(
            bundle_dir if bundle_dir is not None else self.store_root
        )
        # Continuous-telemetry mode: resolve the rotation policy once
        # (config wins, env opt-in otherwise); the lifetime run is
        # pinned open so retention never reclaims its segments while
        # the controller stands.
        from yuma_simulation_tpu.telemetry.flight import (
            RotationPolicy,
            rotation_from_env,
        )
        from yuma_simulation_tpu.telemetry.ops import OpsPlane

        if cfg.flight_rotation is True:
            self.rotation = RotationPolicy()
        elif cfg.flight_rotation:
            self.rotation = cfg.flight_rotation
        else:
            self.rotation = rotation_from_env()
        self.recorder = FlightRecorder(
            self.bundle_dir, rotation=self.rotation
        )
        self.run = RunContext()
        #: Run ids a prior incarnation registered open and never closed
        #: — a clean shutdown always closes its run, so a stale marker
        #: means SIGKILL/crash. The first cycle ledgers one
        #: ``controller_restarted`` per stale run INSIDE its span (the
        #: ledger stamps trace context from the active run), which is
        #: the typed cause behind process-loss incidents.
        self._stale_runs: list[str] = []
        if self.rotation is not None:
            self._stale_runs = [
                r
                for r in self.recorder.open_run_ids()
                if r != self.run.run_id
            ]
            self.recorder.mark_run_open(self.run.run_id)
        from yuma_simulation_tpu.telemetry.slo import get_slo_engine

        #: The live ops plane (debug vars/spans/profile) — transport-
        #: free; an embedding host (or the soak harness) mounts it.
        self.ops = OpsPlane(
            self.bundle_dir,
            registry=get_registry(),
            slo_engine=get_slo_engine(),
            run=self.run,
        )
        self._profiled = False
        #: durable quarantine ledger (reloaded on restart).
        self.ledger = FailureLedger(self.bundle_dir / "ledger.jsonl")
        self._quarantined: set[tuple[int, int]] = {
            (int(r["netuid"]), int(r["block"]))
            for r in self.ledger.entries("subnet_quarantined")
            if "netuid" in r and "block" in r
        }
        #: netuid -> (head block, wall time the head last MOVED).
        self._progress: dict[int, tuple[int, float]] = {}
        self._stalled: set[int] = set()
        #: netuid -> earliest wall time of the next poll (slow tier).
        self._next_poll: dict[int, float] = {}
        #: test-only crash/fault points: name -> callable(netuid,
        #: version); "post_publish" fires between the window's fleet +
        #: cache publication and the watermark advance.
        self.test_hooks: dict[str, Callable] = {}
        registry = get_registry()
        self._staleness_gauge = registry.gauge(
            "replay_staleness_seconds",
            help="worst-case age of the oldest unswept archive suffix",
        )
        self._live_gauge = registry.gauge(
            "subnets_live",
            help="subnets on the fast poll tier (not stalled)",
        )
        self._swept_counter = registry.counter(
            "windows_swept_total",
            help="incremental windows published by the replay controller",
        )
        self._quarantine_counter = registry.counter(
            "snapshots_quarantined_total",
            help="corrupt snapshot blobs quarantined by the controller",
        )
        from yuma_simulation_tpu.telemetry.incident import IncidentEngine

        #: Incident intelligence: per-cycle tick feeds the time-series
        #: store from the live registry, ledgers detector anomalies,
        #: and appends correlated incident state to incidents.jsonl.
        self.incidents = IncidentEngine(
            self.ledger,
            self.recorder,
            registry=registry,
            source=self.run.run_id,
        )

    # -- quarantine -----------------------------------------------------

    def _usable(self, netuid: int, entry) -> bool:
        """True iff the entry's blob loads and verifies. A corrupt blob
        is quarantined durably (once) and excluded from every window
        this and any future controller compiles."""
        if (netuid, entry.block) in self._quarantined:
            return False
        try:
            self.archive.load(netuid, entry.block)
            return True
        except ArchiveError as exc:
            self._quarantined.add((netuid, entry.block))
            self.ledger.append(
                "subnet_quarantined",
                netuid=int(netuid),
                block=int(entry.block),
                key=entry.key,
                reason=str(exc),
            )
            log_event(
                logger,
                "subnet_quarantined",
                netuid=int(netuid),
                block=int(entry.block),
                reason=str(exc),
            )
            self._quarantine_counter.inc()
            return False

    # -- windows --------------------------------------------------------

    def _pair_dir(self, netuid: int, version: str) -> pathlib.Path:
        return (
            self.store_root
            / f"subnet_{int(netuid)}"
            / version_slug(version)
        )

    def _inflight_path(self, netuid: int, version: str) -> pathlib.Path:
        return self._pair_dir(netuid, version) / "inflight.json"

    def _load_inflight(
        self, netuid: int, version: str
    ) -> Optional[WindowSpec]:
        path = self._inflight_path(netuid, version)
        if not path.exists():
            return None
        try:
            payload = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError):
            return None  # torn marker: fall through to a fresh window
        if "blocks" not in payload:
            return None  # committed marker ({}) — no in-flight window
        try:
            return WindowSpec.from_json(payload)
        except ControllerError:
            return None

    def _plan_window(
        self, netuid: int, version: str, timeline: list
    ) -> Optional[WindowSpec]:
        """The next window for one pair, resume-aware: an in-flight
        spec whose base still matches the watermark is reused verbatim
        (same blocks -> same store -> already-published units satisfy
        instantly); otherwise the quarantine-filtered suffix past the
        watermark becomes a fresh window."""
        wm = self.watermarks.load(netuid, version)
        base_block = wm["block"] if wm is not None else None
        inflight = self._load_inflight(netuid, version)
        if inflight is not None and inflight.base_block == base_block:
            return inflight
        pending = [
            e
            for e in timeline
            if (base_block is None or e.block > base_block)
            and self._usable(netuid, e)
        ]
        if not pending:
            return None
        epoch_offset = wm["epochs"] if wm is not None else 0
        prior_key = wm["baseline_key"] if wm is not None else ""
        blocks = [e.block for e in pending]
        if prior_key:
            try:
                self.cache.final_state(prior_key)
            except StateCacheError:
                # The carry was evicted (or predates final-state
                # publication): rebuild the pair from genesis — still
                # exactly-once-published (the full window is its own
                # deterministic store) and bitwise by definition.
                log_event(
                    logger,
                    "state_cache_miss",
                    netuid=int(netuid),
                    version=version,
                    baseline=prior_key[:16],
                    reason="controller carry unavailable; full rebuild",
                )
                prior_key, epoch_offset, base_block = "", 0, None
                blocks = [
                    e.block
                    for e in timeline
                    if self._usable(netuid, e)
                ]
                if not blocks:
                    return None
        swept_and_window = [
            e
            for e in timeline
            if e.block <= blocks[-1]
            and (netuid, e.block) not in self._quarantined
        ]
        store = (
            self._pair_dir(netuid, version)
            / f"window_{blocks[0]}_{blocks[-1]}"
        )
        return WindowSpec(
            netuid=int(netuid),
            version=version,
            blocks=tuple(blocks),
            epochs_per_snapshot=self.cfg.epochs_per_snapshot,
            epoch_offset=int(epoch_offset),
            prior_baseline_key=prior_key,
            base_block=base_block,
            scenario_fingerprint=entries_fingerprint(swept_and_window),
            store=str(store),
        )

    def sweep_window(self, spec: WindowSpec) -> dict:
        """Execute one pinned window end to end: durable intent ->
        fleet grid (suffix-resumed, canaried, at-most-once published)
        -> cache baseline extension -> bitwise cross-check -> watermark
        advance. Crash-safe at every boundary (module docstring)."""
        from yuma_simulation_tpu.fabric.scheduler import (
            FleetConfig,
            run_fleet_grid,
        )
        from yuma_simulation_tpu.models.config import YumaConfig

        cfg = self.cfg
        config = cfg.config if cfg.config is not None else YumaConfig()
        netuid, version = spec.netuid, spec.version
        self._pair_dir(netuid, version).mkdir(parents=True, exist_ok=True)
        # Pin the window membership BEFORE dispatch: a controller
        # killed past this point resumes THIS window even if the
        # archive grew meanwhile — newer blocks wait for the next one.
        publish_atomic(
            self._inflight_path(netuid, version),
            json.dumps(spec.to_json(), sort_keys=True).encode(),
        )
        scenario = self.archive.scenario_for_blocks(
            netuid,
            spec.blocks,
            epochs_per_snapshot=spec.epochs_per_snapshot,
        )
        carry = None
        if spec.prior_baseline_key:
            carry = self.cache.final_state(spec.prior_baseline_key)
        store = pathlib.Path(spec.store)
        store.mkdir(parents=True, exist_ok=True)
        publish_atomic(
            store / "window.json",
            json.dumps(spec.to_json(), sort_keys=True).encode(),
        )
        fleet = FleetConfig(
            directory=store,
            unit_size=cfg.unit_size,
            canary_fraction=cfg.canary_fraction,
            lease_ttl_seconds=cfg.lease_ttl_seconds,
            max_wait_seconds=cfg.max_wait_seconds,
        )
        out = run_fleet_grid(
            scenario,
            version,
            fleet,
            axes={"bond_alpha": [float(config.bond_alpha)]},
            tag=(
                f"replay-controller:{netuid}:{version_slug(version)}:"
                f"{spec.blocks[0]}-{spec.blocks[-1]}"
            ),
            initial_state=carry,
            epoch_offset=spec.epoch_offset,
        )
        if carry is not None:
            meta = self.cache.extend_baseline(
                spec.prior_baseline_key,
                scenario,
                scenario_fingerprint=spec.scenario_fingerprint,
                config=config,
            )
        else:
            # From-scratch builds pin engine="xla": every fleet grid
            # unit computes on the xla rung, and the bitwise
            # incremental contract needs baseline and fleet on ONE
            # engine for the pair's whole lifetime.
            meta = self.cache.build_baseline(
                scenario,
                version,
                config,
                scenario_fingerprint=spec.scenario_fingerprint,
                stride=cfg.stride,
                engine="xla",
            )
        fleet_div = np.asarray(out["dividends"])[0]
        cached_div = self.cache.load_baseline(meta.key)["dividends"][
            spec.epoch_offset :
        ]
        if not np.array_equal(fleet_div, cached_div):
            raise ControllerError(
                f"window subnet={netuid} {version!r} blocks "
                f"{spec.blocks[0]}..{spec.blocks[-1]}: fleet dividends "
                "are not bitwise the extended baseline's suffix — a "
                "carrier broke the suffix-resume contract"
            )
        hook = self.test_hooks.get("post_publish")
        if hook is not None:
            hook(netuid, version)
        suffix_epochs = len(spec.blocks) * spec.epochs_per_snapshot
        total_epochs = spec.epoch_offset + suffix_epochs
        self.watermarks.advance(
            netuid,
            version,
            block=spec.blocks[-1],
            epochs=total_epochs,
            baseline_key=meta.key,
            window_store=spec.store,
        )
        # {} = committed: the next cycle plans a fresh window.
        publish_atomic(self._inflight_path(netuid, version), b"{}")
        report = out["report"]
        self.ledger.append(
            "window_swept",
            netuid=int(netuid),
            version=version,
            block_from=int(spec.blocks[0]),
            block_to=int(spec.blocks[-1]),
            suffix_epochs=suffix_epochs,
            total_epochs=total_epochs,
            resumed=bool(carry is not None),
            units=int(report.units_published),
            canaries=int(report.canaries_run),
            drift=int(report.drift_events),
            store=spec.store,
        )
        self.ledger.append(
            "watermark_advanced",
            netuid=int(netuid),
            version=version,
            block=int(spec.blocks[-1]),
            epochs=total_epochs,
            baseline=meta.key[:16],
        )
        log_event(
            logger,
            "window_swept",
            level=logging.INFO,
            netuid=int(netuid),
            version=version,
            block_from=int(spec.blocks[0]),
            block_to=int(spec.blocks[-1]),
            suffix_epochs=suffix_epochs,
            total_epochs=total_epochs,
        )
        log_event(
            logger,
            "watermark_advanced",
            level=logging.INFO,
            netuid=int(netuid),
            version=version,
            block=int(spec.blocks[-1]),
            epochs=total_epochs,
        )
        self._swept_counter.inc()
        return {
            "netuid": netuid,
            "version": version,
            "blocks": list(spec.blocks),
            "baseline_key": meta.key,
            "suffix_epochs": suffix_epochs,
            "total_epochs": total_epochs,
        }

    # -- the cycle ------------------------------------------------------

    def _observe_subnet(
        self, netuid: int, timeline: list, now: float
    ) -> None:
        """Stall tracking + ingest events for one polled subnet."""
        head = timeline[-1].block if timeline else -1
        prev = self._progress.get(netuid)
        if prev is None or head > prev[0]:
            if prev is not None and head > prev[0]:
                new = sum(1 for e in timeline if e.block > prev[0])
                self.ledger.append(
                    "subnet_ingested",
                    netuid=int(netuid),
                    new_blocks=new,
                    head_block=int(head),
                )
                log_event(
                    logger,
                    "subnet_ingested",
                    level=logging.INFO,
                    netuid=int(netuid),
                    new_blocks=new,
                    head_block=int(head),
                )
            self._progress[netuid] = (head, now)
            if netuid in self._stalled:
                self._stalled.discard(netuid)
                self._next_poll.pop(netuid, None)
        elif (
            netuid not in self._stalled
            and now - prev[1] > self.cfg.stall_deadline_seconds
        ):
            self._stalled.add(netuid)
            self.ledger.append(
                "subnet_stalled",
                netuid=int(netuid),
                head_block=int(head),
                stalled_seconds=round(now - prev[1], 3),
            )
            log_event(
                logger,
                "subnet_stalled",
                netuid=int(netuid),
                head_block=int(head),
                stalled_seconds=round(now - prev[1], 3),
            )

    def _staleness(
        self, netuid: int, version: str, pending: bool, now: float
    ) -> float:
        """Seconds the pair's oldest unswept suffix has waited. Fully
        drained -> 0. Anchored on the durable watermark timestamp when
        one exists (conservative: survives controller restarts, which
        is exactly when freshness debt matters), else on the wall time
        this controller first saw the subnet's head move."""
        if not pending:
            return 0.0
        wm = self.watermarks.load(netuid, version)
        if wm is not None and isinstance(wm.get("t"), (int, float)):
            return max(0.0, now - wm["t"])
        prev = self._progress.get(netuid)
        return max(0.0, now - prev[1]) if prev is not None else 0.0

    def run_cycle(self) -> CycleReport:
        """One poll pass over every subnet: observe, quarantine, plan,
        shed, sweep, and publish the flight bundle. Safe to call from
        a fresh process at any time — all inputs are durable."""
        from yuma_simulation_tpu.telemetry.metrics import get_registry
        from yuma_simulation_tpu.telemetry.runctx import span
        from yuma_simulation_tpu.telemetry.slo import (
            get_slo_engine,
            observe_event,
        )

        report = CycleReport()
        with self.run.activate(), span("replay_cycle"):
            # Publish the OPEN cycle span before any ledger-appending
            # work: every quarantine/stall/sweep record carries this
            # span's identity, and a SIGKILL before the end-of-cycle
            # publish must not leave them dangling (``obsreport
            # --check`` resolves every ledger record to a recorded
            # span; a status="open" span satisfies it, and the
            # end-of-cycle publish replaces it with the closed form).
            try:
                self.recorder.record(self.run)
            except Exception:
                logger.exception("open-span publish failed")
            now = time.time()
            work: list[tuple[int, int, WindowSpec]] = []
            staleness: dict[int, float] = {}
            for netuid in self.archive.subnets():
                if now < self._next_poll.get(netuid, 0.0):
                    report.subnets_seen += 1
                    report.subnets_stalled += 1
                    continue
                try:
                    timeline = self.archive.timeline(netuid)
                except ArchiveError as exc:
                    logger.warning(
                        "subnet %d timeline unreadable: %s", netuid, exc
                    )
                    continue
                report.subnets_seen += 1
                self._observe_subnet(netuid, timeline, now)
                if netuid in self._stalled:
                    report.subnets_stalled += 1
                    self._next_poll[netuid] = (
                        now + self.cfg.slow_poll_seconds
                    )
                pair_stale = 0.0
                for version in self.cfg.versions:
                    spec = self._plan_window(netuid, version, timeline)
                    if spec is not None:
                        work.append(
                            (
                                self.cfg.priorities.get(netuid, 0),
                                netuid,
                                spec,
                            )
                        )
                    pair_stale = max(
                        pair_stale,
                        self._staleness(
                            netuid, version, spec is not None, now
                        ),
                    )
                staleness[netuid] = pair_stale
            report.subnets_live = report.subnets_seen - (
                report.subnets_stalled
            )
            # Freshness verdicts BEFORE sweeping: the SLO judges the
            # backlog as found, so a killed controller's debt burns the
            # budget on the first post-restart cycle and recovery shows
            # up as the verdicts flipping good on later cycles.
            for netuid, stale in staleness.items():
                observe_event(
                    "replay_fresh",
                    stale <= self.cfg.freshness_budget_seconds,
                )
            report.max_staleness_seconds = max(
                staleness.values(), default=0.0
            )
            self._staleness_gauge.set(report.max_staleness_seconds)
            self._live_gauge.set(report.subnets_live)
            # Highest priority first; shed the tail past the budget
            # (they stay pending and age toward the freshness SLO,
            # which is the backpressure signal operators alert on).
            work.sort(key=lambda w: (-w[0], w[1], w[2].version))
            budget = self.cfg.max_windows_per_cycle
            if budget is not None and len(work) > budget:
                report.windows_shed = len(work) - budget
                work = work[:budget]
            if (
                work
                and self.cfg.profile_window_seconds > 0
                and not self._profiled
            ):
                # One guarded device-profile window over the first
                # cycle that actually sweeps (--profile-window): the
                # single-flight latch + auto-stop deadline live in the
                # ops plane; the artifact registers into the bundle.
                self._profiled = True
                try:
                    self.ops.debug_profile(
                        self.cfg.profile_window_seconds, mode="trace"
                    )
                except Exception:  # noqa: BLE001 — observation only
                    logger.warning(
                        "controller profile window failed", exc_info=True
                    )
            for _, netuid, spec in work:
                self.sweep_window(spec)
                report.windows_swept += 1
                report.swept.append(
                    (
                        netuid,
                        spec.version,
                        spec.blocks[0],
                        spec.blocks[-1],
                    )
                )
            # Incident intelligence, inside the cycle span so every
            # anomaly_detected / incident_* ledger record resolves to a
            # recorded span: first surface any crash a prior
            # incarnation left behind, then tick the engine over this
            # cycle's ledger + registry state.
            try:
                for stale_run in self._stale_runs:
                    self.ledger.append("controller_restarted", run=stale_run)
                    log_event(logger, "controller_restarted", run=stale_run)
                self._stale_runs = []
                self.incidents.tick()
            except Exception:  # noqa: BLE001 — observation only
                logger.exception("incident tick failed")
        report.snapshots_quarantined = len(self._quarantined)
        try:
            engine = get_slo_engine()
            engine.evaluate()  # burn state current before the snapshot
            self.recorder.record(self.run, registry=get_registry())
            self.recorder.record_slo(engine)
        except Exception:
            logger.exception("flight bundle publish failed")
        return report

    def run_forever(
        self,
        *,
        stop: Optional[Callable[[], bool]] = None,
        max_cycles: Optional[int] = None,
    ) -> int:
        """Poll until `stop()` goes true (or `max_cycles` elapse).
        Returns the number of cycles run."""
        cycles = 0
        try:
            while max_cycles is None or cycles < max_cycles:
                if stop is not None and stop():
                    break
                self.run_cycle()
                cycles += 1
                if stop is not None and stop():
                    break
                time.sleep(self.cfg.poll_seconds)
        finally:
            self.close()
        return cycles

    def close(self) -> None:
        """Graceful exit: publish any in-flight profile window, release
        the retention pin, and seal the live segment so the bundle on
        disk is whole. Idempotent; a SIGKILLed controller simply skips
        this — the next reader tolerates the torn tail."""
        try:
            self.ops.close()
        except Exception:  # noqa: BLE001 — shutdown must not raise
            logger.warning("ops-plane close failed", exc_info=True)
        if self.rotation is not None:
            try:
                self.recorder.mark_run_closed(self.run.run_id)
                self.recorder.seal_live_segment()
            except Exception:  # noqa: BLE001 — shutdown must not raise
                logger.warning("final segment seal failed", exc_info=True)


# -------------------------------------------------------- helper host


def run_host(
    archive: SnapshotArchive,
    cache: StateCache,
    store_root: Union[str, pathlib.Path],
    *,
    poll_seconds: float = 0.25,
    unit_size: int = 8,
    canary_fraction: float = 1.0,
    lease_ttl_seconds: float = 30.0,
    stop: Optional[Callable[[], bool]] = None,
    max_idle_polls: Optional[int] = None,
) -> int:
    """A helper fleet host for the controller's windows: scan the pair
    directories for in-flight :class:`WindowSpec` markers, reconstruct
    the identical scenario (``scenario_for_blocks`` over the spec's
    pinned blocks) and carry (the shared cache's final state), and join
    the window's fleet store through the ordinary lease-claim path
    (``finalize=False`` — the controller owns collection and the
    watermark commit). A host whose carry is unavailable skips the
    window rather than inventing a different resume point; the manifest
    carry digest would reject it anyway. Returns the number of windows
    joined."""
    from yuma_simulation_tpu.fabric.scheduler import (
        FleetConfig,
        run_fleet_grid,
    )
    from yuma_simulation_tpu.models.config import YumaConfig

    store_root = pathlib.Path(store_root)
    config = YumaConfig()
    joined = 0
    idle = 0
    while True:
        if stop is not None and stop():
            break
        specs: list[WindowSpec] = []
        for marker in sorted(
            store_root.glob("subnet_*/*/inflight.json")
        ):
            try:
                payload = json.loads(marker.read_text(encoding="utf-8"))
            except (OSError, json.JSONDecodeError):
                continue
            if "blocks" not in payload:
                continue
            try:
                specs.append(WindowSpec.from_json(payload))
            except ControllerError:
                continue
        progressed = False
        for spec in specs:
            carry = None
            if spec.prior_baseline_key:
                try:
                    carry = cache.final_state(spec.prior_baseline_key)
                except StateCacheError:
                    continue  # stale resume point: not ours to invent
            try:
                scenario = archive.scenario_for_blocks(
                    spec.netuid,
                    spec.blocks,
                    epochs_per_snapshot=spec.epochs_per_snapshot,
                )
            except ArchiveError:
                continue  # the controller quarantines; we just skip
            fleet = FleetConfig(
                directory=spec.store,
                unit_size=unit_size,
                canary_fraction=canary_fraction,
                lease_ttl_seconds=lease_ttl_seconds,
            )
            run_fleet_grid(
                scenario,
                spec.version,
                fleet,
                axes={"bond_alpha": [float(config.bond_alpha)]},
                tag=(
                    f"replay-host:{spec.netuid}:"
                    f"{version_slug(spec.version)}:"
                    f"{spec.blocks[0]}-{spec.blocks[-1]}"
                ),
                initial_state=carry,
                epoch_offset=spec.epoch_offset,
                finalize=False,
            )
            joined += 1
            progressed = True
        if progressed:
            idle = 0
        else:
            idle += 1
            if max_idle_polls is not None and idle >= max_idle_polls:
                break
        time.sleep(poll_seconds)
    return joined
