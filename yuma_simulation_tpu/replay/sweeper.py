"""The trailing-window scheduled sweep: timelines x variants -> fleet.

The chain-replay service's batch half (ROADMAP item 5): for every
subnet timeline in the archive and every requested Yuma variant,
compile the trailing window into the epoch-varying replay scenario and
run it as lease-claimed :func:`..fabric.scheduler.run_fleet_grid` units
— numerics canaries on, so every unit's per-epoch fingerprints ride the
fleet store's ``numerics.jsonl`` and ``tools/driftreport.py --check
--require`` gates the published bundle exactly like every other drill
artifact. Each (subnet, variant) pair gets its own fleet store (one
manifest = one scenario+version grid); N processes invoked with the
same ``store_root`` split the work through the fabric's ordinary
lease-claim path.

After each pair's fleet units publish, the sweep refreshes that pair's
:mod:`.statecache` baseline (segmented suffix-resume build, carry
checkpointed every ``stride`` epochs) — the warm state the serve tier's
what-ifs resume from, so the nightly sweep is also what keeps the
what-if API's cache hit rate high.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import pathlib
import re
from typing import Optional, Sequence, Union

import numpy as np

from yuma_simulation_tpu.replay.archive import SnapshotArchive
from yuma_simulation_tpu.replay.statecache import StateCache
from yuma_simulation_tpu.utils.checkpoint import publish_atomic

logger = logging.getLogger(__name__)


def version_slug(version: str) -> str:
    """Filesystem-safe variant name (``"Yuma 1 (paper)"`` ->
    ``"yuma-1-paper"``)."""
    return re.sub(r"[^a-z0-9]+", "-", version.lower()).strip("-")


@dataclasses.dataclass
class SweepOutcome:
    """One (subnet, variant) pair's sweep result."""

    netuid: int
    version: str
    store: str
    units_completed: int
    canaries_run: int
    drift_events: int
    baseline_key: str

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def sweep_trailing_window(
    archive: SnapshotArchive,
    cache: StateCache,
    *,
    store_root: Union[str, pathlib.Path],
    versions: Sequence[str],
    subnets: Optional[Sequence[int]] = None,
    window: Optional[int] = None,
    epochs_per_snapshot: int = 4,
    stride: int = 8,
    canary_fraction: float = 1.0,
    unit_size: int = 8,
    config=None,
) -> dict:
    """Run the trailing-window sweep (module docstring). Returns the
    summary dict also published at ``<store_root>/sweep_summary.json``:
    per-pair unit/canary/drift counts, the fleet store paths (what CI
    gates with ``driftreport --check --require``), and the refreshed
    baseline keys."""
    from yuma_simulation_tpu.fabric.scheduler import (
        FleetConfig,
        run_fleet_grid,
    )
    from yuma_simulation_tpu.models.config import YumaConfig

    config = config if config is not None else YumaConfig()
    store_root = pathlib.Path(store_root)
    store_root.mkdir(parents=True, exist_ok=True)
    targets = list(subnets) if subnets is not None else archive.subnets()
    if not targets:
        raise ValueError(f"archive {archive.root} holds no timelines")
    if not versions:
        raise ValueError("sweep_trailing_window needs at least one version")
    outcomes: list[SweepOutcome] = []
    for netuid in targets:
        scenario = archive.window_scenario(
            netuid, window=window, epochs_per_snapshot=epochs_per_snapshot
        )
        fingerprint = archive.timeline_fingerprint(netuid, window=window)
        for version in versions:
            store = store_root / f"subnet_{netuid}" / version_slug(version)
            fleet = FleetConfig(
                directory=store,
                canary_fraction=canary_fraction,
                unit_size=unit_size,
            )
            # One-point grid on a default-valued axis: the baseline
            # trajectory as ONE lease-claimed, canaried, at-most-once-
            # published fleet unit (what-if sweeps over real axes ride
            # the same seam with more points).
            out = run_fleet_grid(
                scenario,
                version,
                fleet,
                axes={"bond_alpha": [float(config.bond_alpha)]},
                tag=f"replay:{netuid}:{version_slug(version)}",
            )
            report = out["report"]
            meta = cache.build_baseline(
                scenario,
                version,
                config,
                scenario_fingerprint=fingerprint,
                stride=stride,
            )
            # The fleet unit and the cache baseline simulate one
            # trajectory through two carriers; both are pinned bitwise
            # to the monolithic engine elsewhere, so a mismatch HERE
            # means a carrier broke its contract — surface it loudly.
            fleet_div = np.asarray(out["dividends"])[0]
            cached_div = cache.load_baseline(meta.key)["dividends"]
            if fleet_div.shape != cached_div.shape:
                raise RuntimeError(
                    f"replay sweep subnet {netuid} {version!r}: fleet "
                    f"dividends {fleet_div.shape} vs cached baseline "
                    f"{cached_div.shape}"
                )
            outcomes.append(
                SweepOutcome(
                    netuid=netuid,
                    version=version,
                    store=str(store),
                    units_completed=int(report.units_published),
                    canaries_run=int(report.canaries_run),
                    drift_events=int(report.drift_events),
                    baseline_key=meta.key,
                )
            )
            logger.info(
                "replay sweep subnet=%d version=%s units=%d canaries=%d "
                "drift=%d baseline=%s",
                netuid,
                version,
                report.units_published,
                report.canaries_run,
                report.drift_events,
                meta.key[:16],
            )
    summary = {
        "subnets": targets,
        "versions": list(versions),
        "window": window,
        "epochs_per_snapshot": epochs_per_snapshot,
        "outcomes": [o.to_json() for o in outcomes],
        "stores": [o.store for o in outcomes],
        "drift_events": sum(o.drift_events for o in outcomes),
        "canaries_run": sum(o.canaries_run for o in outcomes),
        "units_completed": sum(o.units_completed for o in outcomes),
    }
    publish_atomic(
        store_root / "sweep_summary.json",
        json.dumps(summary, indent=2, sort_keys=True).encode(),
    )
    return summary
