"""The chain-replay CLI: one-shot drill, continuous-controller modes,
and the multi-process chaos soak.

Modes (mutually exclusive):

- ``--drill``      — the CI ``replay`` lane's one-shot engine (below);
- ``--controller`` — the standing continuous-replay controller
  (:mod:`.controller`): poll the archive, sweep watermark suffixes
  forever (or ``--cycles N``);
- ``--host``       — a helper fleet host joining the controller's
  in-flight windows through the lease-claim path;
- ``--writer``     — the synthetic archive feed the soak's chaos rides
  on (stall + torn-blob injections, :mod:`.soak`);
- ``--soak``       — the CI ``soak`` lane's engine: writer, controller,
  and host as real processes, SIGKILLs mid-sweep, and a verdict from
  the durable artifacts only (:func:`.soak.run_soak`).

``python -m yuma_simulation_tpu.replay --drill --bundle-dir DIR`` runs
the whole product loop end to end on CPU, deterministically:

1. seed a synthetic 3-snapshot timeline into ``DIR/archive`` (the
   foundry generator — no network, no fixtures);
2. run the trailing-window fleet sweep over it (every requested variant
   as lease-claimed, 100%-canaried fleet units) into ``DIR/store`` —
   the driftreport-gated bundles — refreshing the epoch-state cache at
   ``DIR/cache``;
3. serve two identical what-ifs through a real HTTP server mounted on
   the archive with a FRESH state cache (flight bundle at
   ``DIR/serve``): the first is the typed **state_cache_miss** that
   builds and checkpoints the baseline, the second a **state_cache
   hit** that re-simulates only the suffix, adds **zero AOT builds**,
   and returns bitwise the first's deltas.

CI then gates the artifacts with ``obsreport --check`` (serve bundle +
fleet stores) and ``driftreport --check --require`` (fleet stores),
the same gates every other drill bundle passes. Exit 0 only when every
expectation held and the sweep saw no drift.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

DRILL_VERSIONS = ("Yuma 1 (paper)", "Yuma 2 (Adrian-Fish)")


def run_drill(args) -> int:
    import pathlib

    from yuma_simulation_tpu.replay import (
        SnapshotArchive,
        StateCache,
        synthetic_timeline,
        sweep_trailing_window,
    )
    from yuma_simulation_tpu.serve.server import (
        SimulationClient,
        SimulationServer,
        wait_until_ready,
    )
    from yuma_simulation_tpu.serve.service import ServeConfig
    from yuma_simulation_tpu.simulation.aot import process_stats
    from yuma_simulation_tpu.telemetry.metrics import get_registry
    from yuma_simulation_tpu.utils import setup_logging
    from yuma_simulation_tpu.utils.checkpoint import publish_atomic

    setup_logging()
    target = pathlib.Path(args.bundle_dir)
    failures: list[str] = []

    def expect(cond: bool, what: str) -> None:
        print(("ok   " if cond else "FAIL ") + what)
        if not cond:
            failures.append(what)

    # 1. the synthetic timeline (deterministic: same seed -> same bits).
    archive = SnapshotArchive(target / "archive")
    entries = synthetic_timeline(
        archive,
        args.netuid,
        snapshots=3,
        seed=args.seed,
        num_validators=args.validators,
        num_miners=args.miners,
    )
    expect(
        len(entries) == 3
        and [e.block for e in entries]
        == sorted(e.block for e in entries),
        f"timeline seeded: 3 snapshots at blocks "
        f"{[e.block for e in entries]}",
    )

    # 2. the trailing-window fleet sweep (canaries on, stores gated by
    # CI's driftreport pass).
    cache = StateCache(target / "cache")
    summary = sweep_trailing_window(
        archive,
        cache,
        store_root=target / "store",
        versions=list(args.versions),
        epochs_per_snapshot=args.epochs_per_snapshot,
        stride=args.stride,
        canary_fraction=1.0,
        unit_size=1,
    )
    expect(
        summary["units_completed"] == len(args.versions),
        f"fleet sweep published {summary['units_completed']} unit(s) "
        f"across {len(args.versions)} variant(s)",
    )
    expect(
        summary["canaries_run"] >= len(args.versions),
        f"every sweep unit ran its numerics canary "
        f"({summary['canaries_run']} run)",
    )
    expect(
        summary["drift_events"] == 0,
        f"sweep drift-clean (drift_events={summary['drift_events']})",
    )

    # 3. two what-ifs through a real server mounted on the swept state.
    E = 3 * args.epochs_per_snapshot
    perturb_epoch = E - args.epochs_per_snapshot + 1
    spec = {
        "netuid": args.netuid,
        "version": args.versions[0],
        "from_epoch": perturb_epoch,
        "stake_scale": [[1, 2.0]],
        "weight_rows": [[0, [1.0] + [0.0] * (args.miners - 1)]],
    }
    # The serve tier gets its OWN state cache (not the sweep's), so
    # what-if #1 exercises the full miss path end to end — typed
    # state_cache_miss, baseline build, checkpoints published — and
    # what-if #2 proves the hit path returns bitwise the same deltas.
    server = SimulationServer(
        ServeConfig(
            bundle_dir=str(target / "serve"),
            replay_archive_dir=str(target / "archive"),
            replay_cache_dir=str(target / "serve-cache"),
            replay_epochs_per_snapshot=args.epochs_per_snapshot,
            replay_stride=args.stride,
            executable_cache_dir=str(target / "aot"),
        )
    ).start()
    try:
        expect(wait_until_ready(server.url), "server answers /healthz")
        client = SimulationClient(server.url, tenant="replay-drill")
        r = client.replay(args.netuid)
        expect(
            r.status == 200 and r.body.get("epochs") == E,
            f"GET /v1/replay/{args.netuid} -> {E}-epoch window "
            f"(got {r.status} {r.body.get('epochs')})",
        )
        first = client.whatif(spec)
        expect(
            first.status == 200 and first.body.get("status") == "ok",
            f"what-if #1 -> 200 ok (got {first.status} "
            f"{first.body.get('error')})",
        )
        expect(
            first.body.get("cache_hit") is False
            and first.body.get("epochs_simulated") == E,
            f"what-if #1 is the typed miss that builds the baseline "
            f"(got cache_hit={first.body.get('cache_hit')} "
            f"epochs={first.body.get('epochs_simulated')})",
        )
        hits_before = get_registry().counter("state_cache_hits").value
        builds_before = process_stats().builds
        second = client.whatif(spec)
        hits_after = get_registry().counter("state_cache_hits").value
        builds_after = process_stats().builds
        expect(
            second.status == 200 and second.body.get("cache_hit") is True,
            f"what-if #2 is a state_cache_hit (got "
            f"{second.body.get('cache_hit')})",
        )
        expect(
            hits_after == hits_before + 1,
            f"state_cache_hits counted the hit "
            f"({hits_before} -> {hits_after})",
        )
        expect(
            builds_after == builds_before,
            f"what-if #2 added zero AOT builds "
            f"({builds_before} -> {builds_after})",
        )
        suffix = second.body.get("epochs_simulated")
        saved = second.body.get("epochs_saved")
        expect(
            isinstance(suffix, int)
            and isinstance(saved, int)
            and suffix + saved == E
            and suffix <= E - args.stride + args.epochs_per_snapshot
            and saved > 0,
            f"suffix-sized re-simulation: {suffix} of {E} epochs "
            f"({saved} saved)",
        )
        expect(
            first.body.get("total_dividend_delta")
            == second.body.get("total_dividend_delta"),
            "hit-path deltas bitwise the miss-path build's",
        )
    finally:
        server.close()

    publish_atomic(
        target / "drill_summary.json",
        json.dumps(
            {
                "netuid": args.netuid,
                "versions": list(args.versions),
                "stores": summary["stores"],
                "serve_bundle": str(target / "serve"),
                "failures": failures,
            },
            indent=2,
            sort_keys=True,
        ).encode(),
    )
    print(
        f"\nreplay drill {'FAILED' if failures else 'passed'}: "
        f"{len(entries)} snapshots -> {summary['units_completed']} fleet "
        f"unit(s) -> 2 what-ifs (stores: {', '.join(summary['stores'])})"
    )
    return 1 if failures else 0


def run_controller_mode(args) -> int:
    """The standing controller process (``--controller``): one
    :class:`.controller.ReplayController` on the shared archive/cache/
    store, polling until killed (crash-safe by construction — SIGKILL
    at any instant is the soak's bread and butter) or ``--cycles``
    elapse. One cycle line per poll on stdout — the soak parses
    ``shed=`` for the backpressure verdict."""
    import time

    from yuma_simulation_tpu.replay.archive import SnapshotArchive
    from yuma_simulation_tpu.replay.controller import (
        ControllerConfig,
        ReplayController,
    )
    from yuma_simulation_tpu.replay.statecache import StateCache
    from yuma_simulation_tpu.utils import setup_logging

    setup_logging()
    controller = ReplayController(
        SnapshotArchive(args.archive),
        StateCache(args.cache),
        ControllerConfig(
            store_root=args.store,
            versions=tuple(args.versions),
            epochs_per_snapshot=args.epochs_per_snapshot,
            stride=args.stride,
            unit_size=args.unit_size,
            poll_seconds=args.poll,
            slow_poll_seconds=args.slow_poll,
            stall_deadline_seconds=(
                args.stall_deadline
                if args.stall_deadline is not None
                else 10.0
            ),
            freshness_budget_seconds=(
                args.freshness_budget
                if args.freshness_budget is not None
                else 30.0
            ),
            max_windows_per_cycle=args.max_windows,
            lease_ttl_seconds=args.lease_ttl,
            flight_rotation=args.rotate_flight or None,
            profile_window_seconds=args.profile_window,
        ),
    )
    cycles = 0
    try:
        while args.cycles is None or cycles < args.cycles:
            report = controller.run_cycle()
            cycles += 1
            print(
                f"cycle={cycles} swept={report.windows_swept} "
                f"shed={report.windows_shed} "
                f"stalled={report.subnets_stalled} "
                f"quarantined={report.snapshots_quarantined} "
                f"stale={report.max_staleness_seconds:.2f}",
                flush=True,
            )
            time.sleep(args.poll)
    finally:
        controller.close()
    return 0


def run_host_mode(args) -> int:
    """A helper fleet host process (``--host``) for the controller's
    in-flight windows."""
    from yuma_simulation_tpu.replay.archive import SnapshotArchive
    from yuma_simulation_tpu.replay.controller import run_host
    from yuma_simulation_tpu.replay.statecache import StateCache
    from yuma_simulation_tpu.utils import setup_logging

    setup_logging()
    joined = run_host(
        SnapshotArchive(args.archive),
        StateCache(args.cache),
        args.store,
        poll_seconds=args.poll,
        unit_size=args.unit_size,
        lease_ttl_seconds=args.lease_ttl,
        max_idle_polls=args.max_idle_polls,
    )
    print(f"host joined {joined} window(s)", flush=True)
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m yuma_simulation_tpu.replay",
        description=__doc__.split("\n\n")[0],
    )
    mode = parser.add_mutually_exclusive_group()
    mode.add_argument(
        "--drill",
        action="store_true",
        help="run the chain-replay drill (CI smoke; forces the CPU "
        "backend)",
    )
    mode.add_argument(
        "--soak",
        action="store_true",
        help="run the multi-process continuous-replay chaos soak "
        "(CI soak lane; forces the CPU backend)",
    )
    mode.add_argument(
        "--controller",
        action="store_true",
        help="run the standing continuous-replay controller",
    )
    mode.add_argument(
        "--host",
        action="store_true",
        help="run a helper fleet host joining in-flight windows",
    )
    mode.add_argument(
        "--writer",
        action="store_true",
        help="run the soak's synthetic archive feed",
    )
    parser.add_argument(
        "--bundle-dir",
        default="replay-bundle",
        help="drill/soak output root (archive/, cache/, store/, "
        "serve/)",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--netuid", type=int, default=0)
    parser.add_argument(
        "--validators", type=int, default=3,
        help="synthetic subnet validator count",
    )
    parser.add_argument(
        "--miners", type=int, default=4,
        help="synthetic subnet miner count",
    )
    parser.add_argument("--epochs-per-snapshot", type=int, default=4)
    parser.add_argument(
        "--stride", type=int, default=4,
        help="carry-checkpoint stride of the cached baselines",
    )
    parser.add_argument(
        "--versions",
        nargs="+",
        default=list(DRILL_VERSIONS),
        help="Yuma variants to sweep",
    )
    shared = parser.add_argument_group(
        "controller/host/writer", "shared directories"
    )
    shared.add_argument(
        "--archive", default=None, help="snapshot archive directory"
    )
    shared.add_argument(
        "--cache", default=None, help="epoch-state cache directory"
    )
    shared.add_argument(
        "--store", default=None,
        help="controller store root (watermarks, window fleet stores, "
        "flight bundle)",
    )
    ctl = parser.add_argument_group("controller")
    ctl.add_argument("--poll", type=float, default=0.5)
    ctl.add_argument("--slow-poll", type=float, default=5.0)
    # Defaults are per mode (standing controller: 10s/30s; soak: tight
    # enough that the injected downtime overruns the budget), so None
    # here means "mode default".
    ctl.add_argument("--stall-deadline", type=float, default=None)
    ctl.add_argument("--freshness-budget", type=float, default=None)
    ctl.add_argument(
        "--max-windows", type=int, default=None,
        help="windows swept per cycle before shedding (backpressure)",
    )
    ctl.add_argument("--unit-size", type=int, default=8)
    ctl.add_argument("--lease-ttl", type=float, default=30.0)
    ctl.add_argument(
        "--cycles", type=int, default=None,
        help="stop after N cycles (default: run forever)",
    )
    ctl.add_argument(
        "--rotate-flight", action="store_true",
        help="continuous telemetry: rotate the controller's flight "
        "bundle into crash-safe sealed segments (default bounds; "
        "YUMA_TPU_FLIGHT_ROTATE=1 is the env equivalent)",
    )
    ctl.add_argument(
        "--profile-window", type=float, default=0.0,
        help="arm ONE guarded jax.profiler window of this many "
        "seconds over the first cycle that sweeps work (artifact "
        "registers into the bundle's profiles.jsonl; 0 disables)",
    )
    ctl.add_argument(
        "--max-idle-polls", type=int, default=None,
        help="host only: exit after N consecutive idle polls",
    )
    soak = parser.add_argument_group("writer/soak chaos injections")
    soak.add_argument(
        "--subnets", type=int, default=4,
        help="synthetic subnet count",
    )
    soak.add_argument(
        "--rounds", type=int, default=10,
        help="final snapshot count per (unstalled) subnet",
    )
    soak.add_argument(
        "--interval", type=float, default=0.8,
        help="seconds between writer append rounds",
    )
    soak.add_argument(
        "--stall-netuid", type=int, default=-1,
        help="writer: subnet whose feed goes quiet (soak picks the "
        "last subnet)",
    )
    soak.add_argument(
        "--stall-after", type=int, default=3,
        help="snapshot count after which the stalled feed goes quiet",
    )
    soak.add_argument(
        "--corrupt-netuid", type=int, default=1,
        help="subnet that receives the torn-blob injection",
    )
    soak.add_argument(
        "--corrupt-round", type=int, default=5,
        help="snapshot index (1-based) published with a torn blob",
    )
    soak.add_argument(
        "--kill-after", type=float, default=4.0,
        help="soak: seconds before SIGKILLing controller + host",
    )
    soak.add_argument(
        "--downtime", type=float, default=4.0,
        help="soak: seconds the controller stays dead (freshness debt)",
    )
    soak.add_argument("--drain-timeout", type=float, default=300.0)
    soak.add_argument("--recovery-timeout", type=float, default=180.0)
    args = parser.parse_args(argv)

    import pathlib

    if args.controller or args.host or args.writer:
        missing = [
            flag
            for flag, value in (
                ("--archive", args.archive),
                ("--cache", args.cache),
                ("--store", args.store),
            )
            if value is None and not (args.writer and flag != "--archive")
        ]
        if missing:
            parser.error(
                f"{' '.join(missing)} required for this mode"
            )
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        if args.controller:
            return run_controller_mode(args)
        if args.host:
            return run_host_mode(args)
        from yuma_simulation_tpu.replay.soak import run_writer

        return run_writer(args)

    if not (args.drill or args.soak):
        parser.print_help()
        return 2
    target = pathlib.Path(args.bundle_dir)
    if target.exists() and any(target.iterdir()):
        # A resumed drill satisfies sweep units from the prior run's
        # store and hits a pre-warmed cache — refuse, like the other
        # drills do.
        print(
            f"--bundle-dir {args.bundle_dir!r} exists and is not empty; "
            "point the drill at a fresh directory",
            file=sys.stderr,
        )
        return 2
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.soak:
        from yuma_simulation_tpu.replay.soak import run_soak

        return run_soak(args)
    return run_drill(args)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
