"""Per-subnet snapshot timelines: the chain-replay service's archive.

A timeline is an append-only sequence of metagraph snapshots of ONE
subnet at strictly increasing block heights — what an operator's
exporter publishes once per sampling interval and the replay tier
re-simulates forever. The on-disk layout under one archive root::

    <root>/
      subnet_<netuid>/
        timeline.json              # the ordered index (atomic publish)
        objects/<key>.npz          # content-addressed snapshot blobs

Every write rides :func:`..utils.checkpoint.publish_atomic` (temp +
fsync + rename + dir fsync), so a crash at any instant leaves either
the previous timeline or the new one — never a half-written index, and
never an index entry whose blob is missing (the blob publishes FIRST).
Blobs are content-addressed by the sha256 of their serialized bytes:
appending the same snapshot twice is an idempotent no-op, while a
different snapshot claiming an existing block height is a typed
:class:`ArchiveError` (chain history does not rewrite).

:func:`synthetic_timeline` seeds a deterministic timeline from the
foundry's :func:`..foundry.metagraph.synthetic_snapshot` generator —
what the CI replay drill and the tests run on, no network and no
fixture blobs. :func:`SnapshotArchive.window_scenario` compiles the
trailing window of a timeline into the epoch-varying dense
:class:`..scenarios.base.Scenario` every engine rung, ``plan_dispatch``
and the fleet/serve tiers consume unchanged — closing the seam
:mod:`..foundry.metagraph` left open ("replaying a snapshot SEQUENCE
is the chain-replay service's job").
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import io
import json
import logging
import os
import pathlib
from typing import Optional, Sequence, Union

import numpy as np

from yuma_simulation_tpu.foundry.metagraph import (
    MetagraphSnapshot,
    SnapshotError,
    _check_snapshot,
    synthetic_snapshot,
)
from yuma_simulation_tpu.scenarios.base import Scenario
from yuma_simulation_tpu.utils.checkpoint import publish_atomic

logger = logging.getLogger(__name__)

TIMELINE_FORMAT = "yuma-replay-timeline-v1"


class ArchiveError(ValueError):
    """A timeline operation that violates the archive contract
    (non-monotone block, shape drift mid-timeline, rewritten history,
    unknown subnet, corrupt index)."""


@dataclasses.dataclass(frozen=True)
class TimelineEntry:
    """One indexed snapshot: where it is and what shape it carries —
    enough for admission pricing without touching the blob."""

    block: int
    key: str  # sha256 of the serialized blob (content address)
    validators: int
    miners: int

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, payload: dict) -> "TimelineEntry":
        try:
            return cls(
                block=int(payload["block"]),
                key=str(payload["key"]),
                validators=int(payload["validators"]),
                miners=int(payload["miners"]),
            )
        except (KeyError, TypeError, ValueError) as exc:
            raise ArchiveError(f"corrupt timeline entry: {exc}") from None


def _serialize_snapshot(snap: MetagraphSnapshot) -> bytes:
    """Canonical npz bytes of one snapshot (dense — the blobs are the
    replay tier's working format, not the operator exchange format;
    sparse exports ingest through the foundry loader first)."""
    buf = io.BytesIO()
    np.savez(
        buf,
        netuid=np.int64(snap.netuid),
        block=np.int64(snap.block),
        stakes=snap.stakes,
        weights=snap.weights,
    )
    return buf.getvalue()


def _deserialize_snapshot(blob: bytes) -> MetagraphSnapshot:
    with np.load(io.BytesIO(blob)) as data:
        return MetagraphSnapshot(
            netuid=int(data["netuid"]),
            block=int(data["block"]),
            stakes=np.asarray(data["stakes"], np.float32),
            weights=np.asarray(data["weights"], np.float32),
        )


class SnapshotArchive:
    """The append-only per-subnet timeline store (module docstring)."""

    def __init__(self, root: Union[str, pathlib.Path]):
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    # -- layout ---------------------------------------------------------

    def _subnet_dir(self, netuid: int) -> pathlib.Path:
        return self.root / f"subnet_{int(netuid)}"

    def _timeline_path(self, netuid: int) -> pathlib.Path:
        return self._subnet_dir(netuid) / "timeline.json"

    def _blob_path(self, netuid: int, key: str) -> pathlib.Path:
        return self._subnet_dir(netuid) / "objects" / f"{key}.npz"

    # -- reads ----------------------------------------------------------

    def subnets(self) -> list[int]:
        """Netuids with a published timeline, ascending."""
        out = []
        for p in self.root.glob("subnet_*"):
            tail = p.name.split("_", 1)[1]
            if tail.isdigit() and (p / "timeline.json").exists():
                out.append(int(tail))
        return sorted(out)

    def timeline(self, netuid: int) -> list[TimelineEntry]:
        """The ordered index of one subnet (oldest first). Unknown
        subnet -> typed :class:`ArchiveError`."""
        path = self._timeline_path(netuid)
        if not path.exists():
            raise ArchiveError(
                f"no timeline for subnet {netuid} in {self.root}"
            )
        try:
            payload = json.loads(path.read_text(encoding="utf-8"))
        except json.JSONDecodeError as exc:
            raise ArchiveError(
                f"corrupt timeline index for subnet {netuid}: {exc}"
            ) from None
        if payload.get("format") != TIMELINE_FORMAT:
            raise ArchiveError(
                f"subnet {netuid}: timeline format "
                f"{payload.get('format')!r}, want {TIMELINE_FORMAT!r}"
            )
        return [TimelineEntry.from_json(e) for e in payload.get("entries", [])]

    def load(self, netuid: int, block: int) -> MetagraphSnapshot:
        """One archived snapshot by block height, digest-verified: a
        blob whose bytes no longer hash to its content address is
        corruption, surfaced as a typed error rather than NaNs in a
        consensus reduction."""
        entry = next(
            (e for e in self.timeline(netuid) if e.block == int(block)), None
        )
        if entry is None:
            raise ArchiveError(
                f"subnet {netuid} has no snapshot at block {block}"
            )
        path = self._blob_path(netuid, entry.key)
        try:
            blob = path.read_bytes()
        except OSError as exc:
            raise ArchiveError(
                f"subnet {netuid} block {block}: blob missing ({exc})"
            ) from None
        if hashlib.sha256(blob).hexdigest() != entry.key:
            raise ArchiveError(
                f"subnet {netuid} block {block}: blob bytes do not match "
                f"content address {entry.key[:16]} (corruption)"
            )
        return _check_snapshot(_deserialize_snapshot(blob))

    def latest(self, netuid: int) -> MetagraphSnapshot:
        entries = self.timeline(netuid)
        if not entries:
            raise ArchiveError(f"subnet {netuid} timeline is empty")
        return self.load(netuid, entries[-1].block)

    # -- append ---------------------------------------------------------

    @contextlib.contextmanager
    def _append_lock(self, netuid: int):
        """Serialize the append read-modify-write ACROSS PROCESSES: two
        racing appenders of different blocks would otherwise both read
        the same index and the second rename would silently drop the
        first's entry (lost update). One advisory `flock` per subnet —
        writers of different subnets never contend, readers never take
        it (the blob-before-index publish order already guarantees a
        reader mid-publish sees either the old index or a new entry
        whose blob exists). Held across the blob AND index publishes so
        the idempotent-re-append / history-rewrite checks race-free."""
        import fcntl

        subnet_dir = self._subnet_dir(netuid)
        subnet_dir.mkdir(parents=True, exist_ok=True)
        fd = os.open(
            subnet_dir / ".append.lock", os.O_CREAT | os.O_RDWR, 0o644
        )
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            # Closing the fd releases the flock.
            os.close(fd)

    def append(self, snap: MetagraphSnapshot) -> TimelineEntry:
        """Append one snapshot to its subnet's timeline under the
        archive contract: strictly monotone block heights, stable
        [V, M] shape, blob-before-index publish order, one appender at
        a time per subnet (cross-process advisory lock — racing
        appenders serialize instead of losing updates). Re-appending an
        identical (block, bytes) snapshot is an idempotent no-op."""
        try:
            _check_snapshot(snap)
        except SnapshotError as exc:
            raise ArchiveError(str(exc)) from None
        with self._append_lock(snap.netuid):
            return self._append_locked(snap)

    def _append_locked(self, snap: MetagraphSnapshot) -> TimelineEntry:
        entries = []
        if self._timeline_path(snap.netuid).exists():
            entries = self.timeline(snap.netuid)
        blob = _serialize_snapshot(snap)
        key = hashlib.sha256(blob).hexdigest()
        entry = TimelineEntry(
            block=int(snap.block),
            key=key,
            validators=snap.num_validators,
            miners=snap.num_miners,
        )
        if entries:
            existing = next(
                (e for e in entries if e.block == entry.block), None
            )
            if existing is not None:
                if existing.key == entry.key:
                    return existing  # idempotent re-publish
                raise ArchiveError(
                    f"subnet {snap.netuid}: block {entry.block} is already "
                    f"archived with different contents ({existing.key[:16]} "
                    f"vs {entry.key[:16]}; archived chain history does not "
                    "rewrite)"
                )
            last = entries[-1]
            if entry.block <= last.block:
                raise ArchiveError(
                    f"subnet {snap.netuid}: block {entry.block} does not "
                    f"extend the timeline (last block {last.block}; "
                    "archived chain history is append-only)"
                )
            if (entry.validators, entry.miners) != (
                last.validators,
                last.miners,
            ):
                raise ArchiveError(
                    f"subnet {snap.netuid}: snapshot shape "
                    f"[{entry.validators}, {entry.miners}] drifts from the "
                    f"timeline's [{last.validators}, {last.miners}] — a "
                    "re-shaped subnet starts a new archive root"
                )
        blob_path = self._blob_path(snap.netuid, key)
        blob_path.parent.mkdir(parents=True, exist_ok=True)
        # Blob first, index second: a crash between the two leaves an
        # unreferenced blob (harmless garbage), never an index entry
        # pointing at nothing.
        publish_atomic(blob_path, blob)
        payload = {
            "format": TIMELINE_FORMAT,
            "netuid": int(snap.netuid),
            "entries": [e.to_json() for e in entries + [entry]],
        }
        publish_atomic(
            self._timeline_path(snap.netuid),
            json.dumps(payload, sort_keys=True).encode(),
        )
        logger.info(
            "archived subnet %d block %d (%dx%d, %d entries)",
            snap.netuid,
            snap.block,
            entry.validators,
            entry.miners,
            len(entries) + 1,
        )
        return entry

    # -- replay compilation ---------------------------------------------

    def window_entries(
        self, netuid: int, *, window: Optional[int] = None
    ) -> list[TimelineEntry]:
        entries = self.timeline(netuid)
        if not entries:
            raise ArchiveError(f"subnet {netuid} timeline is empty")
        if window is not None:
            if window < 1:
                raise ArchiveError(f"window must be >= 1, got {window}")
            entries = entries[-window:]
        return entries

    def entries_after(self, netuid: int, block: int) -> list[TimelineEntry]:
        """Timeline entries strictly past ``block`` (oldest first) —
        the continuous-replay controller's suffix query: everything a
        durable watermark has not swept yet. Empty list when the
        timeline has nothing newer (a subnet being fully drained is the
        steady state, not an error)."""
        return [e for e in self.timeline(netuid) if e.block > int(block)]

    def scenario_for_blocks(
        self,
        netuid: int,
        blocks: Sequence[int],
        *,
        epochs_per_snapshot: int = 4,
    ) -> Scenario:
        """Compile an EXPLICIT ascending block list into the
        epoch-varying scenario (same normalization and epoch layout as
        :meth:`window_scenario`, which delegates here) — how the
        controller compiles a watermark-to-head suffix window, and how
        a joining fleet host reconstructs the identical scenario from a
        published window spec. The list may skip quarantined blocks:
        the compiled scenario covers exactly the blocks given, in
        order."""
        if epochs_per_snapshot < 1:
            raise ArchiveError(
                f"epochs_per_snapshot must be >= 1, got {epochs_per_snapshot}"
            )
        blocks = [int(b) for b in blocks]
        if not blocks:
            raise ArchiveError(
                f"subnet {netuid}: cannot compile an empty block list"
            )
        if blocks != sorted(set(blocks)):
            raise ArchiveError(
                f"subnet {netuid}: block list must be strictly ascending, "
                f"got {blocks}"
            )
        W_parts, S_parts = [], []
        for block in blocks:
            snap = self.load(netuid, block)
            row_sums = snap.weights.sum(axis=1, keepdims=True)
            W_n = np.divide(
                snap.weights,
                row_sums,
                out=np.zeros_like(snap.weights),
                where=row_sums > 0,
            ).astype(np.float32)
            S_n = (snap.stakes / snap.stakes.sum()).astype(np.float32)
            W_parts.append(np.tile(W_n[None], (epochs_per_snapshot, 1, 1)))
            S_parts.append(np.tile(S_n[None], (epochs_per_snapshot, 1)))
        return self._dense_scenario(
            netuid,
            blocks,
            np.concatenate(W_parts),
            np.concatenate(S_parts),
            epochs_per_snapshot,
        )

    def window_scenario(
        self,
        netuid: int,
        *,
        window: Optional[int] = None,
        epochs_per_snapshot: int = 4,
    ) -> Scenario:
        """Compile the trailing ``window`` snapshots into ONE
        epoch-varying scenario: snapshot ``i``'s normalized weights and
        stakes hold for epochs ``[i*K, (i+1)*K)`` — the replay tier's
        model of a chain whose metagraph re-samples every K epochs.
        The result is a plain dense Scenario, so plans, donor packing,
        numerics capture, and the suffix-resume engine contract apply
        unchanged."""
        entries = self.window_entries(netuid, window=window)
        return self.scenario_for_blocks(
            netuid,
            [e.block for e in entries],
            epochs_per_snapshot=epochs_per_snapshot,
        )

    def _dense_scenario(
        self,
        netuid: int,
        blocks: Sequence[int],
        weights: np.ndarray,
        stakes: np.ndarray,
        epochs_per_snapshot: int,
    ) -> Scenario:
        E, V, M = weights.shape
        validators = [f"uid {v}" for v in range(V)]
        scenario = Scenario(
            name=(
                f"replay netuid={netuid} blocks "
                f"{blocks[0]}..{blocks[-1]} "
                f"({len(blocks)} snapshots x {epochs_per_snapshot} epochs)"
            ),
            validators=validators,
            base_validator=validators[
                int(np.argmax(stakes.sum(axis=0)))
            ],
            weights=weights,
            stakes=stakes,
            num_epochs=E,
            servers=[f"Server {m + 1}" for m in range(M)],
        )
        scenario.validate(normalized=True)
        from yuma_simulation_tpu.foundry.dsl import record_scenario_generated

        record_scenario_generated()
        return scenario

    def timeline_fingerprint(
        self, netuid: int, *, window: Optional[int] = None
    ) -> str:
        """Content address of one subnet's trailing window — what the
        state cache keys baselines on, so a timeline that grew a new
        snapshot (or a different window) can never serve a stale
        baseline."""
        entries = self.window_entries(netuid, window=window)
        return entries_fingerprint(entries)


def entries_fingerprint(entries: Sequence[TimelineEntry]) -> str:
    """Content address of an explicit entry list — the same hash
    :meth:`SnapshotArchive.timeline_fingerprint` computes over a
    trailing window, exposed for the controller's quarantine-filtered
    and watermark-bounded windows (the state cache keys baselines on
    exactly the entries a window COMPILED, not the timeline's raw
    contents)."""
    h = hashlib.sha256()
    for e in entries:
        h.update(f"{e.block}:{e.key}\n".encode())
    return h.hexdigest()


def synthetic_timeline(
    archive: SnapshotArchive,
    netuid: int,
    *,
    snapshots: int = 3,
    seed: int = 0,
    num_validators: int = 256,
    num_miners: int = 4096,
    base_block: int = 1000,
    blocks_per_snapshot: int = 100,
) -> list[TimelineEntry]:
    """Seed a deterministic synthetic timeline (CI / tests / the replay
    drill): ``snapshots`` foundry-generated snapshots at blocks
    ``base_block + i * blocks_per_snapshot``, each drawn from a seed
    derived as ``seed + i`` so consecutive snapshots are correlated the
    way consecutive chain samples are distinct. Same arguments ->
    bitwise-identical timeline on any host (the generator is pure
    numpy on explicit rngs). Idempotent: re-seeding an archive that
    already holds the identical prefix extends or no-ops."""
    entries = []
    for i in range(snapshots):
        snap = synthetic_snapshot(
            seed + i,
            num_validators=num_validators,
            num_miners=num_miners,
            netuid=netuid,
            block=base_block + i * blocks_per_snapshot,
        )
        entries.append(archive.append(snap))
    return entries
