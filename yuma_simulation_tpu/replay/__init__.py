"""Chain-replay service: snapshot timelines, epoch-state caching, what-ifs.

The flagship product tier (ROADMAP item 5), four pillars compiled down
to carriers every other tier already consumes:

- :mod:`.archive` — append-only per-subnet snapshot timelines
  (content-addressed blobs, atomic publish, typed :class:`ArchiveError`,
  deterministic synthetic generator for CI);
- :mod:`.statecache` — incremental epoch-state prefix caching over the
  engine's suffix-resume contract (``simulate(initial_state=...)``),
  LRU-bounded and content-addressed, bitwise against full runs;
- :mod:`.whatif` — frozen serializable perturbation specs compiled onto
  a cached baseline, returning per-validator/per-miner dividend deltas
  while re-simulating only the suffix;
- :mod:`.sweeper` — the trailing-window scheduled sweep: every variant
  x every subnet timeline as lease-claimed, canaried fleet units with
  driftreport-gated bundles.

:class:`ReplayService` is the glue the serve tier (``POST /v1/whatif``,
``GET /v1/replay/...``) and the drill (``python -m
yuma_simulation_tpu.replay --drill``) share.
"""

from __future__ import annotations

import logging
import pathlib
import threading
from typing import Optional, Union

from yuma_simulation_tpu.replay.archive import (  # noqa: F401
    ArchiveError,
    SnapshotArchive,
    TimelineEntry,
    synthetic_timeline,
)
from yuma_simulation_tpu.replay.statecache import (  # noqa: F401
    BaselineMeta,
    StateCache,
    StateCacheError,
    baseline_key,
)
from yuma_simulation_tpu.replay.controller import (  # noqa: F401
    ControllerConfig,
    ControllerError,
    CycleReport,
    ReplayController,
    WatermarkStore,
    WindowSpec,
    run_host,
)
from yuma_simulation_tpu.replay.sweeper import (  # noqa: F401
    sweep_trailing_window,
    version_slug,
)
from yuma_simulation_tpu.replay.whatif import (  # noqa: F401
    WhatIfError,
    WhatIfResult,
    WhatIfSpec,
    run_whatif,
)

logger = logging.getLogger(__name__)

__all__ = [
    "ArchiveError",
    "BaselineMeta",
    "ControllerConfig",
    "ControllerError",
    "CycleReport",
    "ReplayController",
    "ReplayService",
    "SnapshotArchive",
    "StateCache",
    "StateCacheError",
    "TimelineEntry",
    "WatermarkStore",
    "WhatIfError",
    "WhatIfResult",
    "WhatIfSpec",
    "WindowSpec",
    "baseline_key",
    "run_host",
    "run_whatif",
    "sweep_trailing_window",
    "synthetic_timeline",
    "version_slug",
]


class ReplayService:
    """Archive + state cache behind one object: what the serve tier
    mounts (``ServeConfig.replay_archive_dir`` /
    ``replay_cache_dir``) and the drill drives directly.

    `describe(spec)` is the admission-time half: pure index/meta reads
    — subnet shape, epoch count, and the checkpoint a what-if would
    resume from — so the serve tier can price the request
    SUFFIX-SIZED through ``plan_dispatch`` without materializing a
    single scenario array. `whatif(spec)` is the dispatch-time half."""

    def __init__(
        self,
        archive_dir: Union[str, pathlib.Path],
        cache_dir: Union[str, pathlib.Path],
        *,
        window: Optional[int] = None,
        epochs_per_snapshot: int = 4,
        stride: int = 8,
        max_baselines: int = 64,
        config=None,
    ):
        from yuma_simulation_tpu.models.config import YumaConfig

        self.archive = SnapshotArchive(archive_dir)
        self.cache = StateCache(cache_dir, max_baselines=max_baselines)
        self.window = window
        self.epochs_per_snapshot = int(epochs_per_snapshot)
        self.stride = int(stride)
        self.config = config if config is not None else YumaConfig()
        # Compiled-window memo (fingerprint -> Scenario): a what-if
        # burst against one subnet must not re-tile the [E, V, M] stack
        # per request. Bounded; guarded by the lock (jaxlint JX101).
        self._lock = threading.Lock()
        self._scenarios: dict = {}

    # -- index reads (GET /v1/replay/...) --------------------------------

    def index(self) -> dict:
        subnets = []
        for netuid in self.archive.subnets():
            entries = self.archive.timeline(netuid)
            subnets.append(
                {
                    "netuid": netuid,
                    "snapshots": len(entries),
                    "first_block": entries[0].block if entries else None,
                    "last_block": entries[-1].block if entries else None,
                    "validators": entries[-1].validators if entries else None,
                    "miners": entries[-1].miners if entries else None,
                }
            )
        return {
            "subnets": subnets,
            "window": self.window,
            "epochs_per_snapshot": self.epochs_per_snapshot,
            "cached_baselines": len(self.cache.keys()),
        }

    def timeline_info(self, netuid: int) -> dict:
        entries = self.archive.timeline(netuid)
        window = self.archive.window_entries(netuid, window=self.window)
        fingerprint = self.archive.timeline_fingerprint(
            netuid, window=self.window
        )
        baselines = []
        for key in self.cache.keys():
            meta = self.cache.meta(key)
            if meta is not None and meta.scenario_fingerprint == fingerprint:
                baselines.append(
                    {
                        "key": meta.key,
                        "version": meta.version,
                        "engine": meta.engine,
                        "epochs": meta.epochs,
                        "stride": meta.stride,
                        "checkpoints": list(meta.checkpoints),
                    }
                )
        return {
            "netuid": netuid,
            "entries": [e.to_json() for e in entries],
            "window_blocks": [e.block for e in window],
            "epochs": len(window) * self.epochs_per_snapshot,
            "baselines": baselines,
        }

    # -- what-if resolution ----------------------------------------------

    def _resolve_key(self, spec: WhatIfSpec) -> tuple:
        """(fingerprint, engine, key, (E, V, M)) — all host arithmetic
        and index reads, zero compiles, zero array builds."""
        from jax import numpy as jnp

        from yuma_simulation_tpu.simulation.planner import plan_dispatch

        entries = self.archive.window_entries(
            spec.netuid, window=self.window
        )
        V, M = entries[-1].validators, entries[-1].miners
        E = len(entries) * self.epochs_per_snapshot
        fingerprint = self.archive.timeline_fingerprint(
            spec.netuid, window=self.window
        )
        engine = plan_dispatch(
            f"replay:baseline:{spec.version}",
            (E, V, M),
            spec.version,
            self.config,
            jnp.float32,
        ).engine
        key = baseline_key(
            scenario_fingerprint=fingerprint,
            version=spec.version,
            config=self.config,
            dtype="float32",
            epochs=E,
            stride=self.stride,
            engine=engine,
        )
        return fingerprint, engine, key, (E, V, M)

    def describe(self, spec: WhatIfSpec) -> dict:
        """Admission-time pricing facts for one what-if: the full and
        SUFFIX shapes (the suffix is what the dispatch actually costs),
        and whether a baseline is already cached."""
        fingerprint, engine, key, (E, V, M) = self._resolve_key(spec)
        if spec.from_epoch >= E:
            raise WhatIfError(
                f"from_epoch {spec.from_epoch} is beyond the window's "
                f"{E} epochs"
            )
        meta = self.cache.meta(key)
        resume = (
            self.cache.resume_epoch(key, spec.from_epoch)
            if meta is not None
            else 0
        )
        return {
            "key": key,
            "fingerprint": fingerprint,
            "engine": engine,
            "epochs": E,
            "validators": V,
            "miners": M,
            "cached": meta is not None,
            "resume_epoch": resume,
            "suffix_epochs": E - resume,
        }

    def _window_scenario(self, netuid: int, fingerprint: str):
        with self._lock:
            hit = self._scenarios.get(fingerprint)
        if hit is not None:
            return hit
        scenario = self.archive.window_scenario(
            netuid,
            window=self.window,
            epochs_per_snapshot=self.epochs_per_snapshot,
        )
        with self._lock:
            if len(self._scenarios) >= 8:
                self._scenarios.pop(next(iter(self._scenarios)))
            self._scenarios[fingerprint] = scenario
        return scenario

    def whatif(self, spec: WhatIfSpec) -> WhatIfResult:
        """Execute one what-if: resume from the cached baseline when a
        usable checkpoint exists; otherwise record the typed miss,
        build (and checkpoint) the baseline, then run the perturbed
        suffix from the checkpoint the build just published — the miss
        pays the baseline build (all E epochs), never a THIRD
        end-to-end pass, and the next what-if on this baseline is a
        suffix-sized hit.

        The build runs OUTSIDE the service lock: `build_baseline` is
        idempotent (content-addressed key, atomic publishes, concurrent
        builders race safely), so a racing miss on the same key at
        worst duplicates work — it never blocks hits on OTHER baselines
        behind a multi-second build."""
        fingerprint, engine, key, (E, _V, _M) = self._resolve_key(spec)
        scenario = self._window_scenario(spec.netuid, fingerprint)
        meta = self.cache.meta(key)
        if meta is None:
            self.cache.record_miss(
                key, total_epochs=E, reason="baseline_not_built"
            )
            meta = self.cache.build_baseline(
                scenario,
                spec.version,
                self.config,
                scenario_fingerprint=fingerprint,
                stride=self.stride,
                engine=engine,
            )
            result = run_whatif(
                self.cache,
                meta,
                scenario,
                self.config,
                spec,
                use_cache=True,  # the checkpoints the build just wrote
            )
            # Honest miss accounting: the request paid for the full
            # baseline build, so it reports as a miss simulating all E
            # epochs regardless of how the perturbed half dispatched.
            result.cache_hit = False
            result.resume_epoch = 0
            result.epochs_saved = 0
            result.epochs_simulated = E
            return result
        return run_whatif(
            self.cache,
            meta,
            scenario,
            self.config,
            spec,
            use_cache=True,
            record=True,
        )
