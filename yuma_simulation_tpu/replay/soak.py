"""The continuous-replay chaos soak: the CI ``soak`` lane's engine.

``python -m yuma_simulation_tpu.replay --soak --bundle-dir DIR`` stands
up the whole continuous-replay stack as REAL processes and breaks it
on purpose:

- a **writer process** appends synthetic snapshots to N subnet
  timelines on a cadence (the cross-process archive append lock is on
  the hot path), stops feeding one subnet (the stall injection), and
  publishes one snapshot with a TORN blob — a timeline entry whose
  content address the stored bytes no longer hash to (the corruption
  injection);
- a **controller process** (:mod:`.controller`) sweeps every
  (subnet x variant) suffix past its durable watermark as incremental
  fleet windows;
- a **helper fleet host process** joins the in-flight windows through
  the ordinary lease-claim path;
- a **serve tier** (in the orchestrator, its own flight bundle) takes
  continuous what-if traffic throughout.

Mid-soak the orchestrator SIGKILLs the fleet host and then the
controller, waits out a downtime window while the writer keeps
appending (freshness debt accrues against the durable watermark
timestamps), and restarts the controller COLD. The soak passes only
when the durable artifacts prove self-healing end to end:

- zero client-visible what-if errors through the kill;
- the torn blob is quarantined (typed ``subnet_quarantined`` ledger
  record) and its subnet keeps draining past it;
- the starved subnet emits ``subnet_stalled`` and demotes to the slow
  poll tier;
- every (subnet x variant) watermark drains to its timeline head, each
  window is published exactly once (no duplicate ``window_swept``),
  and the fleet-unit ledgers show the restart re-simulated only
  genuinely in-flight units;
- the ``replay_freshness`` SLO fast-burns on the first post-restart
  cycles and recovers once the backlog drains;
- the controller's final baselines are BITWISE a from-scratch
  re-simulation of the full (quarantine-filtered) timelines;
- the flight bundles and every window's fleet store pass the same
  ``obsreport --check`` / ``driftreport --check --require`` /
  ``sloreport --check`` gates as every other drill;
- incident intelligence correlated every injected fault to exactly
  one durable incident with the right typed cause (torn blob ->
  snapshot-corruption on the corrupted subnet only, starved subnet ->
  subnet-stall, controller SIGKILL -> process-loss resolved by
  post-restart progress); the downtime legitimately starves EVERY
  subnet past the stall deadline, so those restart-collateral stalls
  are true positives that must all come out resolved — while the
  serve control arm shows ZERO incidents and ``incidentreport
  --check`` gates the record of truth.
"""

from __future__ import annotations

import hashlib
import json
import pathlib
import re
import subprocess
import sys
import threading
import time
from collections import Counter
from typing import Optional

BASE_BLOCK = 1000
BLOCKS_PER_SNAPSHOT = 100


def _snapshot_block(index: int) -> int:
    return BASE_BLOCK + index * BLOCKS_PER_SNAPSHOT


# ------------------------------------------------------------- writer


def _append_torn(archive, snap) -> None:
    """Archive `snap` with a TORN blob: the timeline entry carries the
    content address of the fully serialized bytes, but the published
    blob is truncated to half — what a non-atomic blob writer dying
    mid-write would have left behind. Reaches past the public
    ``append`` on purpose: ``append`` can only publish sound blobs,
    and corrupting after a normal append races the controller's sweep
    of the very block under test. Subsequent idempotent re-appends of
    the same snapshot match the (sound) index key and no-op, so the
    corruption is stable for the controller to find."""
    from yuma_simulation_tpu.replay.archive import (
        TIMELINE_FORMAT,
        TimelineEntry,
        _serialize_snapshot,
    )
    from yuma_simulation_tpu.utils.checkpoint import publish_atomic

    blob = _serialize_snapshot(snap)
    key = hashlib.sha256(blob).hexdigest()
    with archive._append_lock(snap.netuid):
        entries = []
        if archive._timeline_path(snap.netuid).exists():
            entries = archive.timeline(snap.netuid)
        if any(e.block == int(snap.block) for e in entries):
            return  # already archived (idempotent, like append)
        entry = TimelineEntry(
            block=int(snap.block),
            key=key,
            validators=snap.num_validators,
            miners=snap.num_miners,
        )
        blob_path = archive._blob_path(snap.netuid, key)
        blob_path.parent.mkdir(parents=True, exist_ok=True)
        publish_atomic(blob_path, blob[: max(1, len(blob) // 2)])
        payload = {
            "format": TIMELINE_FORMAT,
            "netuid": int(snap.netuid),
            "entries": [e.to_json() for e in entries + [entry]],
        }
        publish_atomic(
            archive._timeline_path(snap.netuid),
            json.dumps(payload, sort_keys=True).encode(),
        )
    print(
        f"[writer] TORN blob injected: subnet {snap.netuid} "
        f"block {snap.block}",
        flush=True,
    )


def run_writer(args) -> int:
    """The standing archive feed (``--writer``): one snapshot per
    subnet per round, skipping the stall-injected subnet past its
    cutoff and publishing the corruption-injected snapshot with a torn
    blob. Rounds are absolute snapshot counts, so the writer is
    idempotent over restarts the same way ``synthetic_timeline`` is."""
    from yuma_simulation_tpu.foundry.metagraph import synthetic_snapshot
    from yuma_simulation_tpu.replay.archive import (
        SnapshotArchive,
        synthetic_timeline,
    )

    archive = SnapshotArchive(args.archive)
    for rnd in range(3, args.rounds + 1):
        for netuid in range(args.subnets):
            if netuid == args.stall_netuid and rnd > args.stall_after:
                continue  # the stall injection: this feed went quiet
            if (
                netuid == args.corrupt_netuid
                and rnd == args.corrupt_round
            ):
                snap = synthetic_snapshot(
                    args.seed + netuid * 1000 + (rnd - 1),
                    num_validators=args.validators,
                    num_miners=args.miners,
                    netuid=netuid,
                    block=_snapshot_block(rnd - 1),
                )
                _append_torn(archive, snap)
                continue
            synthetic_timeline(
                archive,
                netuid,
                snapshots=rnd,
                seed=args.seed + netuid * 1000,
                num_validators=args.validators,
                num_miners=args.miners,
            )
        print(f"[writer] round {rnd}/{args.rounds} appended", flush=True)
        time.sleep(args.interval)
    print("[writer] done", flush=True)
    return 0


# ------------------------------------------------------ orchestration


def _gate(tool: str, argv: list) -> int:
    """One artifact gate, in-process when the repo's ``tools`` package
    is importable (the soak already paid the interpreter + jax import;
    a subprocess per window store would dominate the lane's wall
    clock), else as the ordinary CLI subprocess."""
    try:
        import importlib

        mod = importlib.import_module(f"tools.{tool}")
    except ImportError:
        return subprocess.run(
            [sys.executable, "-m", f"tools.{tool}", *argv]
        ).returncode
    return int(mod.main(list(argv)))


def run_soak(args) -> int:
    import os

    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.replay.archive import (
        SnapshotArchive,
        entries_fingerprint,
        synthetic_timeline,
    )
    from yuma_simulation_tpu.replay.controller import WatermarkStore
    from yuma_simulation_tpu.replay.statecache import StateCache
    from yuma_simulation_tpu.serve.server import (
        SimulationClient,
        SimulationServer,
        wait_until_ready,
    )
    from yuma_simulation_tpu.serve.service import ServeConfig
    from yuma_simulation_tpu.telemetry.flight import load_bundle
    from yuma_simulation_tpu.utils import setup_logging
    from yuma_simulation_tpu.utils.checkpoint import (
        publish_atomic,
        read_jsonl_tolerant,
    )

    setup_logging()
    target = pathlib.Path(args.bundle_dir).resolve()
    archive_dir = target / "archive"
    cache_dir = target / "cache"
    store_dir = target / "store"
    logs_dir = target / "logs"
    logs_dir.mkdir(parents=True, exist_ok=True)

    failures: list[str] = []

    def expect(cond: bool, what: str) -> None:
        print(("ok   " if cond else "FAIL ") + what, flush=True)
        if not cond:
            failures.append(what)

    subnets = args.subnets
    stall_netuid = subnets - 1
    corrupt_netuid = args.corrupt_netuid
    if subnets < 3 or corrupt_netuid in (0, stall_netuid):
        print(
            "--soak needs >= 3 subnets with the corruption injection on "
            "a middle netuid (subnet 0 is the bitwise-verify control, "
            "the last subnet is the stall injection)",
            file=sys.stderr,
        )
        return 2
    corrupt_block = _snapshot_block(args.corrupt_round - 1)
    heads = {
        n: _snapshot_block(args.rounds - 1) for n in range(subnets)
    }
    heads[stall_netuid] = _snapshot_block(args.stall_after - 1)

    # 1. Seed every timeline (two snapshots) so the first controller
    # cycle has a full backlog and the shed budget bites immediately.
    archive = SnapshotArchive(archive_dir)
    for n in range(subnets):
        synthetic_timeline(
            archive,
            n,
            snapshots=2,
            seed=args.seed + n * 1000,
            num_validators=args.validators,
            num_miners=args.miners,
        )
    print(f"[soak] seeded {subnets} subnets x 2 snapshots", flush=True)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Continuous-telemetry rotation, byte-bounded small so a soak-length
    # run demonstrably seals >= 2 flight segments: every spawned process
    # (controller, host, writer) inherits the opt-in.
    env["YUMA_TPU_FLIGHT_ROTATE"] = "16384"
    mod = [sys.executable, "-m", "yuma_simulation_tpu.replay"]
    procs: list[subprocess.Popen] = []
    logfiles = []

    def spawn(name: str, extra: list) -> subprocess.Popen:
        log = open(logs_dir / f"{name}.log", "ab")
        logfiles.append(log)
        proc = subprocess.Popen(
            mod + extra, stdout=log, stderr=subprocess.STDOUT, env=env
        )
        procs.append(proc)
        return proc

    common = [
        "--archive", str(archive_dir),
        "--cache", str(cache_dir),
        "--store", str(store_dir),
    ]

    # The soak's own knob defaults: the shed budget must bite (well
    # under the pair count), the freshness budget must be overrun by
    # the injected downtime (else the kill never burns the SLO), and
    # the stall deadline must fire within the post-restart drain.
    max_windows = (
        args.max_windows if args.max_windows is not None else 2
    )
    freshness_budget = (
        args.freshness_budget
        if args.freshness_budget is not None
        else min(2.0, args.downtime / 2)
    )
    stall_deadline = (
        args.stall_deadline if args.stall_deadline is not None else 4.0
    )

    def spawn_controller() -> subprocess.Popen:
        return spawn(
            "controller",
            ["--controller"]
            + common
            + [
                "--versions", *args.versions,
                "--epochs-per-snapshot", str(args.epochs_per_snapshot),
                "--stride", str(args.stride),
                "--unit-size", "1",
                "--poll", "0.25",
                "--freshness-budget", str(freshness_budget),
                "--stall-deadline", str(stall_deadline),
                "--max-windows", str(max_windows),
                "--lease-ttl", "3",
            ],
        )

    server = None
    load_stop = threading.Event()
    load_stats = {"ok": 0, "errors": []}
    try:
        writer = spawn(
            "writer",
            ["--writer"]
            + common
            + [
                "--subnets", str(subnets),
                "--rounds", str(args.rounds),
                "--interval", str(args.interval),
                "--stall-netuid", str(stall_netuid),
                "--stall-after", str(args.stall_after),
                "--corrupt-netuid", str(corrupt_netuid),
                "--corrupt-round", str(args.corrupt_round),
                "--seed", str(args.seed),
                "--validators", str(args.validators),
                "--miners", str(args.miners),
            ],
        )
        host = spawn(
            "host",
            ["--host"]
            + common
            + ["--unit-size", "1", "--poll", "0.25", "--lease-ttl", "3"],
        )
        controller = spawn_controller()

        # 2. Continuous what-if load through a real server mounted on
        # the same (growing) archive, its own cache + flight bundle.
        # The corruption-injected subnet is the controller's problem,
        # not the load's: its full-window scenario is unreadable by
        # construction, so clients steer to the sound subnets.
        server = SimulationServer(
            ServeConfig(
                bundle_dir=str(target / "serve"),
                replay_archive_dir=str(archive_dir),
                replay_cache_dir=str(target / "serve-cache"),
                replay_epochs_per_snapshot=args.epochs_per_snapshot,
                replay_stride=args.stride,
                executable_cache_dir=str(target / "aot"),
            )
        ).start()
        expect(wait_until_ready(server.url), "server answers /healthz")
        load_subnets = [
            n for n in range(subnets) if n != corrupt_netuid
        ]

        def load_loop() -> None:
            client = SimulationClient(server.url, tenant="replay-soak")
            i = 0
            while not load_stop.is_set():
                netuid = load_subnets[i % len(load_subnets)]
                i += 1
                try:
                    r = client.replay(netuid)
                    if r.status != 200:
                        load_stats["errors"].append(
                            f"replay/{netuid} -> {r.status}"
                        )
                        continue
                    epochs = int(r.body["epochs"])
                    w = client.whatif(
                        {
                            "netuid": netuid,
                            "version": args.versions[0],
                            "from_epoch": max(1, epochs - 1),
                            "stake_scale": [[1, 2.0]],
                            "weight_rows": [
                                [0, [1.0] + [0.0] * (args.miners - 1)]
                            ],
                        }
                    )
                    if (
                        w.status != 200
                        or w.body.get("status") != "ok"
                    ):
                        load_stats["errors"].append(
                            f"whatif/{netuid} -> {w.status} "
                            f"{w.body.get('error')}"
                        )
                    else:
                        load_stats["ok"] += 1
                except Exception as exc:  # client-visible by definition
                    load_stats["errors"].append(
                        f"whatif/{netuid} raised {exc!r}"
                    )
                time.sleep(0.35)

        load_thread = threading.Thread(target=load_loop, daemon=True)
        load_thread.start()

        # 3. The chaos: SIGKILL the fleet host, then the controller —
        # most likely mid-window — and keep the writer feeding debt
        # while nothing drains it.
        t0 = time.time()
        time.sleep(args.kill_after)
        host.kill()
        controller.kill()
        host.wait()
        controller.wait()
        print(
            f"[soak] SIGKILLed controller+host at +{time.time() - t0:.1f}s",
            flush=True,
        )
        # Rotation routes the metrics stream into flight segments, so
        # count through load_bundle (root + segments in index order) —
        # appends only ever extend the tail, so positional slicing
        # against this count stays chronological.
        lines_at_kill = len(load_bundle(store_dir).metrics)
        time.sleep(args.downtime)
        controller = spawn_controller()
        print(
            f"[soak] controller restarted COLD at +{time.time() - t0:.1f}s",
            flush=True,
        )

        rc = writer.wait(timeout=args.rounds * args.interval + 120)
        expect(rc == 0, f"writer exited clean (rc={rc})")

        # 4. Drain: every (subnet x variant) watermark reaches its
        # timeline head — including past the quarantined block.
        marks = WatermarkStore(store_dir / "watermarks")

        def drained() -> bool:
            for n in range(subnets):
                for v in args.versions:
                    rec = marks.load(n, v)
                    if rec is None or rec["block"] != heads[n]:
                        return False
            return True

        deadline = time.time() + args.drain_timeout
        while time.time() < deadline and not drained():
            if controller.poll() is not None:
                break  # controller died; fail below with its rc
            time.sleep(0.5)
        expect(
            drained(),
            "every (subnet x variant) watermark drained to its head "
            f"block (controller rc={controller.poll()})",
        )

        load_stop.set()
        load_thread.join(timeout=15)
        expect(
            load_stats["ok"] > 0 and not load_stats["errors"],
            f"what-if load clean through the kill "
            f"({load_stats['ok']} ok, "
            f"errors={load_stats['errors'][:3]})",
        )
        server.close()
        server = None

        # 5. Recovery: the freshness SLO must un-flip before the final
        # bundle capture (sloreport --check fails an active fast burn).
        slo_path = store_dir / "slo.json"

        def fast_burning() -> bool:
            try:
                snap = json.loads(slo_path.read_text(encoding="utf-8"))
            except (OSError, json.JSONDecodeError):
                return True
            state = snap.get("states", {}).get("replay_freshness", {})
            return state.get("state") == "fast_burn"

        deadline = time.time() + args.recovery_timeout
        while time.time() < deadline and fast_burning():
            if controller.poll() is not None:
                break
            time.sleep(0.5)
        expect(
            not fast_burning(),
            "replay_freshness recovered from the kill-induced burn",
        )
        controller.terminate()
        controller.wait(timeout=60)
    finally:
        load_stop.set()
        if server is not None:
            server.close()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        for log in logfiles:
            log.close()

    # ---- verdicts from the durable artifacts only ---------------------
    ledger = read_jsonl_tolerant(store_dir / "ledger.jsonl")
    quarantined = [
        r for r in ledger if r.get("event") == "subnet_quarantined"
    ]
    expect(
        any(
            r.get("netuid") == corrupt_netuid
            and r.get("block") == corrupt_block
            for r in quarantined
        ),
        f"torn blob quarantined (subnet {corrupt_netuid} block "
        f"{corrupt_block})",
    )
    expect(
        any(
            r.get("event") == "subnet_stalled"
            and r.get("netuid") == stall_netuid
            for r in ledger
        ),
        f"starved subnet {stall_netuid} emitted subnet_stalled",
    )

    swept = [r for r in ledger if r.get("event") == "window_swept"]
    by_window = Counter(
        (
            r.get("netuid"),
            r.get("version"),
            r.get("block_from"),
            r.get("block_to"),
        )
        for r in swept
    )
    dupes = {k: c for k, c in by_window.items() if c > 1}
    expect(
        bool(swept) and not dupes,
        f"every window published exactly once "
        f"({len(swept)} windows, duplicates={dupes})",
    )
    expect(
        any(r.get("resumed") for r in swept),
        "incremental windows resumed from cached carry",
    )
    expect(
        all(r.get("drift") == 0 for r in swept),
        "every window drift-clean",
    )

    # Exactly-once unit economy: every store complete, and the global
    # unit_ok count exceeds the published-unit count only by the few
    # genuinely in-flight units the kills forced a second simulation of.
    stores = sorted(
        {r["store"] for r in swept if isinstance(r.get("store"), str)}
    )
    store_problems: list[str] = []
    total_units = 0
    total_unit_ok = 0
    for s in stores:
        sp = pathlib.Path(s)
        try:
            manifest = json.loads(
                (sp / "manifest.json").read_text(encoding="utf-8")
            )
        except (OSError, json.JSONDecodeError) as exc:
            store_problems.append(f"{s}: unreadable manifest ({exc})")
            continue
        num_units = int(manifest["num_units"])
        published = len(list((sp / "results").glob("unit_*.npz")))
        if published != num_units:
            store_problems.append(
                f"{s}: {published}/{num_units} units published"
            )
        total_units += num_units
        hosts_dir = sp / "hosts"
        if hosts_dir.is_dir():
            for host_dir in hosts_dir.iterdir():
                total_unit_ok += sum(
                    1
                    for r in read_jsonl_tolerant(
                        host_dir / "ledger.jsonl"
                    )
                    if r.get("event") == "unit_ok"
                )
    expect(
        bool(stores) and not store_problems,
        f"every window store complete ({len(stores)} stores"
        + (f"; problems={store_problems[:3]}" if store_problems else "")
        + ")",
    )
    resim_slack = 2 * len(args.versions) + 2
    expect(
        total_units <= total_unit_ok <= total_units + resim_slack,
        f"restart re-simulated only in-flight units "
        f"(unit_ok={total_unit_ok} for {total_units} published, "
        f"slack<={resim_slack})",
    )

    # The SLO story, from the metrics stream: no fast burn active at
    # the kill snapshot boundary is not required (startup backlog may
    # legitimately burn) — what must hold is a fast burn AFTER the
    # restart and a final snapshot with none.
    metrics_lines = load_bundle(store_dir).metrics
    post_restart = metrics_lines[lines_at_kill:]

    def burn_active(line: dict) -> float:
        return float(
            (line.get("gauges") or {}).get("slo_fast_burn_active", 0)
        )

    expect(
        any(burn_active(l) >= 1 for l in post_restart),
        "freshness SLO fast-burned after the cold restart",
    )
    expect(
        bool(metrics_lines) and burn_active(metrics_lines[-1]) == 0,
        "no fast burn active at the final snapshot",
    )

    # Backpressure: the controller's own cycle lines prove shedding.
    ctl_text = (logs_dir / "controller.log").read_text(
        encoding="utf-8", errors="replace"
    )
    sheds = [int(m) for m in re.findall(r"shed=(\d+)", ctl_text)]
    expect(
        any(s > 0 for s in sheds),
        f"backlog shed low-priority refreshes "
        f"(max shed={max(sheds, default=0)})",
    )

    # Continuous telemetry: the byte-bounded rotation opt-in must have
    # produced a multi-segment bundle with sealed, crash-safe segments
    # (the obsreport/sloreport gates below then read the same bundle
    # through the segment-aware loader).
    sealed_segments = sorted(
        p.parent.name
        for p in (store_dir / "segments").glob("seg_*/seal.json")
    )
    expect(
        len(sealed_segments) >= 2,
        f"flight recorder sealed >= 2 rotated segments "
        f"({len(sealed_segments)}: {sealed_segments[:4]})",
    )

    # 6. Bitwise: the controller's final incremental baselines against
    # from-scratch re-simulations of the full (quarantine-filtered)
    # timelines — the clean control subnet AND the corrupted one.
    cache = StateCache(cache_dir)
    verify_cache = StateCache(target / "verify-cache")
    config = YumaConfig()
    for netuid in (0, corrupt_netuid):
        entries = [
            e
            for e in archive.timeline(netuid)
            if not (
                netuid == corrupt_netuid and e.block == corrupt_block
            )
        ]
        scenario = archive.scenario_for_blocks(
            netuid,
            [e.block for e in entries],
            epochs_per_snapshot=args.epochs_per_snapshot,
        )
        for version in args.versions:
            rec = marks.load(netuid, version)
            if rec is None:
                expect(False, f"subnet {netuid} {version}: no watermark")
                continue
            meta = verify_cache.build_baseline(
                scenario,
                version,
                config,
                scenario_fingerprint=entries_fingerprint(entries),
                stride=args.stride,
                engine="xla",
            )
            expect(
                meta.key == rec["baseline_key"],
                f"subnet {netuid} {version}: incremental baseline key "
                f"IS the from-scratch key",
            )
            import numpy as np

            incremental = cache.load_baseline(rec["baseline_key"])
            full = verify_cache.load_baseline(meta.key)
            expect(
                np.array_equal(
                    incremental["dividends"], full["dividends"]
                ),
                f"subnet {netuid} {version}: incremental dividends "
                f"bitwise the full re-simulation",
            )

    # 7. Incident intelligence: every injected fault class correlated
    # to exactly one durable incident with the right typed cause, and
    # the unfaulted control arms stayed at zero. This is the proof the
    # correlation engine attributes rather than pattern-matches.
    from yuma_simulation_tpu.telemetry.incident import load_incidents

    incidents = load_incidents(store_dir)
    by_class = Counter(r.get("cause_class") for r in incidents)
    corruption = [
        r
        for r in incidents
        if r.get("cause_class") == "snapshot-corruption"
    ]
    expect(
        len(corruption) == 1
        and corruption[0].get("subject") == f"netuid={corrupt_netuid}"
        and (corruption[0].get("cause") or {}).get("event")
        == "subnet_quarantined"
        and corruption[0].get("state") == "resolved",
        f"torn blob -> exactly one resolved snapshot-corruption "
        f"incident on netuid={corrupt_netuid} "
        f"(got {[r.get('incident') for r in corruption]})",
    )
    stalls = {
        r.get("subject"): r
        for r in incidents
        if r.get("cause_class") == "subnet-stall"
    }
    starved = stalls.get(f"netuid={stall_netuid}")
    expect(
        starved is not None
        and (starved.get("cause") or {}).get("event") == "subnet_stalled",
        f"starved subnet -> a subnet-stall incident on "
        f"netuid={stall_netuid} caused by subnet_stalled "
        f"(got {sorted(stalls)})",
    )
    # The downtime starves EVERY subnet past the stall deadline —
    # those restart-collateral stalls are TRUE positives (the feed
    # really was stale), deduped to one incident per subject by
    # identity, and the drain must have resolved every one of them.
    unresolved = [
        s for s, r in stalls.items() if r.get("state") != "resolved"
    ]
    expect(
        not unresolved,
        f"every subnet-stall incident resolved by the drain "
        f"({len(stalls)} stalled subject(s), "
        f"unresolved={unresolved})",
    )
    losses = [
        r for r in incidents if r.get("cause_class") == "process-loss"
    ]
    expect(
        len(losses) == 1
        and (losses[0].get("cause") or {}).get("event")
        == "controller_restarted",
        f"controller SIGKILL -> exactly one process-loss incident "
        f"(got {[r.get('incident') for r in losses]})",
    )
    expect(
        all(
            r.get("subject") == f"netuid={corrupt_netuid}"
            for r in corruption
        )
        and not any(
            r.get("subject") == "netuid=0" for r in corruption
        ),
        "corruption blamed on the corrupted subnet only "
        f"(classes={dict(by_class)})",
    )
    expect(
        _gate("incidentreport", [str(store_dir), "--check"]) == 0,
        "incidentreport --check green on the controller bundle",
    )
    expect(
        _gate(
            "incidentreport", [str(target / "serve"), "--expect-none"]
        )
        == 0,
        "serve control arm: zero incidents (incidentreport "
        "--expect-none)",
    )

    # 8. The same artifact gates every other drill bundle passes.
    expect(
        _gate("obsreport", [str(store_dir), "--check"]) == 0,
        "obsreport --check green on the controller bundle",
    )
    expect(
        _gate("sloreport", [str(store_dir), "--check", "--require"]) == 0,
        "sloreport --check --require green on the controller bundle",
    )
    expect(
        _gate("obsreport", [str(target / "serve"), "--check"]) == 0,
        "obsreport --check green on the serve bundle",
    )
    gate_failures = 0
    for s in stores:
        if _gate("obsreport", [s, "--check"]) != 0:
            gate_failures += 1
            print(f"FAIL obsreport --check {s}", flush=True)
        if _gate("driftreport", [s, "--check", "--require"]) != 0:
            gate_failures += 1
            print(f"FAIL driftreport --check --require {s}", flush=True)
    expect(
        gate_failures == 0,
        f"obsreport + driftreport green on all {len(stores)} window "
        f"stores",
    )
    if gate_failures:
        failures.append(f"{gate_failures} window-store gate failures")

    publish_atomic(
        target / "soak_summary.json",
        json.dumps(
            {
                "subnets": subnets,
                "versions": list(args.versions),
                "windows_swept": len(swept),
                "stores": stores,
                "units_published": total_units,
                "unit_ok_records": total_unit_ok,
                "whatifs_ok": load_stats["ok"],
                "quarantined_block": corrupt_block,
                "stalled_netuid": stall_netuid,
                "sealed_segments": len(sealed_segments),
                "incidents": {
                    str(cls): int(count)
                    for cls, count in sorted(by_class.items())
                    if cls
                },
                "failures": failures,
            },
            indent=2,
            sort_keys=True,
        ).encode(),
    )
    print(
        f"\nreplay soak {'FAILED' if failures else 'passed'}: "
        f"{len(swept)} windows across {subnets} subnets x "
        f"{len(args.versions)} variant(s), {load_stats['ok']} what-ifs, "
        f"1 torn blob, 1 stall, 2 SIGKILLs"
    )
    return 1 if failures else 0
