"""What-if specs and their suffix-resumed execution.

A :class:`WhatIfSpec` is a frozen, JSON-round-trippable perturbation of
one subnet's baseline trajectory under one Yuma variant: a
hyperparameter delta, validator weight-row overrides, and/or stake
shocks, all taking effect at ``from_epoch`` — the epoch the perturbed
world diverges from the archived baseline. Because nothing before
``from_epoch`` changes, the prefix of the perturbed trajectory is
bitwise the baseline's (scan causality: epoch ``e`` depends only on
inputs ``[0..e]``), so :func:`run_whatif` resumes from the nearest
cached checkpoint ``c <= from_epoch`` and re-simulates only epochs
``[c, E)`` — the :mod:`.statecache` hit path — while producing the
exact bits an uncached end-to-end run of the same perturbed world
yields (``use_cache=False`` computes that reference; the property
suite pins the two equal on every engine rung).

Hyperparameter deltas change the *config* from ``from_epoch`` onward (a
chain governance change taking effect at a block), so their execution
is piecewise: baseline config up to ``from_epoch``, perturbed config
after — two engine dispatches at most, both riding the suffix-resume
carry contract.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from dataclasses import replace

import numpy as np

from yuma_simulation_tpu.replay.statecache import BaselineMeta, StateCache


class WhatIfError(ValueError):
    """A what-if spec that violates the contract (unknown fields, out
    of range indices/epochs, non-settable hyperparameters)."""


#: Config fields a what-if may override — the same request-settable
#: float universe the serve tier's admission accepts (compile-static
#: fields select different programs, which a warm-engine service must
#: not let a payload do).
def _settable_fields() -> tuple[set, set]:
    from yuma_simulation_tpu.models.config import (
        SimulationHyperparameters,
        YumaParams,
    )

    sim = SimulationHyperparameters()
    par = YumaParams()
    sim_fields = {f for f in vars(sim) if f != "consensus_precision"}
    par_fields = {
        f
        for f in vars(par)
        if f
        not in (
            "liquid_alpha",
            "override_consensus_high",
            "override_consensus_low",
        )
    }
    return sim_fields, par_fields


@dataclasses.dataclass(frozen=True)
class WhatIfSpec:
    """One frozen perturbation (module docstring). All collection
    fields are tuples so the spec is hashable and its JSON form is
    canonical."""

    netuid: int
    version: str
    #: the epoch the perturbed world diverges from the baseline —
    #: nothing before it may change (validated).
    from_epoch: int = 0
    #: ``((field, new_value), ...)`` config overrides effective from
    #: ``from_epoch`` (request-settable float fields only).
    hparams: tuple = ()
    #: ``((validator_index, (w_0 .. w_{M-1})), ...)`` replacement weight
    #: rows (re-normalized on application), effective from ``from_epoch``.
    weight_rows: tuple = ()
    #: ``((validator_index, factor), ...)`` stake multipliers effective
    #: from ``from_epoch`` (a stake shock).
    stake_scale: tuple = ()

    def __post_init__(self):
        if self.from_epoch < 0:
            raise WhatIfError(
                f"from_epoch must be >= 0, got {self.from_epoch}"
            )
        if not (self.hparams or self.weight_rows or self.stake_scale):
            raise WhatIfError(
                "a what-if must perturb something: hparams, weight_rows "
                "or stake_scale"
            )
        sim_fields, par_fields = _settable_fields()
        for name, value in self.hparams:
            if name not in sim_fields | par_fields:
                raise WhatIfError(
                    f"hyperparameter {name!r} is not what-if-settable "
                    "(unknown or compile-static)"
                )
            if not isinstance(value, (int, float)) or isinstance(
                value, bool
            ):
                raise WhatIfError(f"hyperparameter {name!r} must be a number")
        for idx, factor in self.stake_scale:
            if not isinstance(idx, int) or idx < 0:
                raise WhatIfError(
                    f"stake_scale validator index must be >= 0, got {idx!r}"
                )
            if (
                not isinstance(factor, (int, float))
                or isinstance(factor, bool)
                or factor < 0
                or not np.isfinite(factor)
            ):
                raise WhatIfError(
                    f"stake_scale factor must be a finite number >= 0, "
                    f"got {factor!r}"
                )
        for idx, row in self.weight_rows:
            if not isinstance(idx, int) or idx < 0:
                raise WhatIfError(
                    f"weight_rows validator index must be >= 0, got {idx!r}"
                )
            arr = np.asarray(row, dtype=np.float64)
            if arr.ndim != 1:
                raise WhatIfError(
                    f"weight row for validator {idx} must be 1-D"
                )
            if not np.isfinite(arr).all() or (arr < 0).any():
                raise WhatIfError(
                    f"weight row for validator {idx} must be finite and "
                    "non-negative"
                )

    # -- serialization ---------------------------------------------------

    def to_json(self) -> dict:
        return {
            "netuid": self.netuid,
            "version": self.version,
            "from_epoch": self.from_epoch,
            "hparams": [[n, float(v)] for n, v in self.hparams],
            "weight_rows": [
                [i, [float(w) for w in row]] for i, row in self.weight_rows
            ],
            "stake_scale": [[i, float(f)] for i, f in self.stake_scale],
        }

    @classmethod
    def from_json(cls, payload: dict) -> "WhatIfSpec":
        if not isinstance(payload, dict):
            raise WhatIfError("what-if spec must be a JSON object")
        known = {
            "netuid",
            "version",
            "from_epoch",
            "hparams",
            "weight_rows",
            "stake_scale",
        }
        extra = set(payload) - known
        if extra:
            raise WhatIfError(
                f"unknown what-if fields {sorted(extra)} (expected a "
                f"subset of {sorted(known)})"
            )
        if "netuid" not in payload or "version" not in payload:
            raise WhatIfError("what-if spec needs 'netuid' and 'version'")
        try:
            netuid = int(payload["netuid"])
            from_epoch = int(payload.get("from_epoch", 0))
        except (TypeError, ValueError) as exc:
            raise WhatIfError(str(exc)) from None

        def pairs(name, cast):
            raw = payload.get(name, [])
            if not isinstance(raw, (list, tuple)):
                raise WhatIfError(f"{name} must be a list of pairs")
            out = []
            for item in raw:
                if not isinstance(item, (list, tuple)) or len(item) != 2:
                    raise WhatIfError(f"{name} entries must be pairs")
                try:
                    out.append(cast(item))
                except (TypeError, ValueError) as exc:
                    # The cast's own failure must stay a TYPED spec
                    # error: admission only converts WhatIfError into a
                    # 400, so a bare ValueError here would surface as a
                    # 503 and burn the serve error-rate SLO on a
                    # payload mistake.
                    raise WhatIfError(
                        f"{name} entry {item!r}: {exc}"
                    ) from None
            return tuple(out)

        return cls(
            netuid=netuid,
            version=str(payload["version"]),
            from_epoch=from_epoch,
            hparams=pairs("hparams", lambda it: (str(it[0]), float(it[1]))),
            weight_rows=pairs(
                "weight_rows",
                lambda it: (int(it[0]), tuple(float(w) for w in it[1])),
            ),
            stake_scale=pairs(
                "stake_scale", lambda it: (int(it[0]), float(it[1]))
            ),
        )

    def spec_key(self) -> str:
        """Content address of the spec (canonical JSON sha256)."""
        return hashlib.sha256(
            json.dumps(self.to_json(), sort_keys=True).encode()
        ).hexdigest()


@dataclasses.dataclass
class WhatIfResult:
    """One executed what-if: the perturbed trajectory, its deltas vs
    the baseline, and the suffix-resume accounting (the epoch-count
    telemetry the acceptance criteria gate on)."""

    spec: WhatIfSpec
    dividends: np.ndarray  # [E, V] perturbed trajectory
    incentives: np.ndarray  # [E, M]
    dividend_delta: np.ndarray  # [E, V] perturbed - baseline
    incentive_delta: np.ndarray  # [E, M]
    cache_hit: bool
    resume_epoch: int
    epochs_simulated: int
    epochs_saved: int
    baseline_key: str

    @property
    def total_dividend_delta(self) -> np.ndarray:  # [V]
        return self.dividend_delta.sum(axis=0)

    @property
    def total_incentive_delta(self) -> np.ndarray:  # [M]
        return self.incentive_delta.sum(axis=0)


def apply_config(config, spec: WhatIfSpec):
    """The perturbed config (hyperparameter overrides applied; the
    caller decides WHEN it takes effect — see :func:`run_whatif`)."""
    if not spec.hparams:
        return config
    sim_fields, par_fields = _settable_fields()
    sim, par = config.simulation, config.yuma_params
    for name, value in spec.hparams:
        if name in sim_fields:
            sim = replace(sim, **{name: float(value)})
        else:
            par = replace(par, **{name: float(value)})
    return replace(config, simulation=sim, yuma_params=par)


def apply_arrays(
    weights: np.ndarray, stakes: np.ndarray, spec: WhatIfSpec
) -> tuple[np.ndarray, np.ndarray]:
    """The perturbed epoch stacks: weight-row overrides (re-normalized)
    and stake shocks applied to every epoch ``>= from_epoch`` of COPIES
    of the inputs. Index bounds are validated against the actual shape
    here (the spec's own validation cannot know V/M)."""
    E, V, M = weights.shape
    if spec.from_epoch >= E:
        raise WhatIfError(
            f"from_epoch {spec.from_epoch} is beyond the baseline's "
            f"{E} epochs"
        )
    W = np.array(weights, copy=True)
    S = np.array(stakes, copy=True)
    k = spec.from_epoch
    for idx, row in spec.weight_rows:
        if idx >= V:
            raise WhatIfError(
                f"weight_rows validator {idx} out of range [0, {V})"
            )
        arr = np.asarray(row, np.float32)
        if arr.shape != (M,):
            raise WhatIfError(
                f"weight row for validator {idx} has {arr.shape[0]} "
                f"miners, the subnet has {M}"
            )
        total = float(arr.sum())
        if total > 0:
            arr = arr / total
        W[k:, idx, :] = arr
    for idx, factor in spec.stake_scale:
        if idx >= V:
            raise WhatIfError(
                f"stake_scale validator {idx} out of range [0, {V})"
            )
        S[k:, idx] *= np.float32(factor)
    return W, S


def run_whatif(
    cache: StateCache,
    meta: BaselineMeta,
    scenario,
    config,
    spec: WhatIfSpec,
    *,
    use_cache: bool = True,
    record: bool = False,
) -> WhatIfResult:
    """Execute one what-if against a cached baseline (module
    docstring). ``use_cache=False`` computes the uncached reference —
    the same piecewise-defined perturbed world simulated end-to-end
    from the zero state — which the cached path must match bitwise.
    ``record=True`` emits the hit/miss telemetry (the caller that owns
    the request — :class:`..replay.ReplayService` — sets it; direct
    library use and reference runs stay telemetry-silent by default so
    bench/test loops don't skew the cache counters)."""
    import dataclasses as dc

    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.simulation.engine import simulate

    config = config if config is not None else YumaConfig()
    E, V, M = np.shape(scenario.weights)
    if (meta.epochs, meta.validators, meta.miners) != (E, V, M):
        raise WhatIfError(
            f"baseline {meta.key[:16]} is [{meta.epochs}, "
            f"{meta.validators}, {meta.miners}], the scenario is "
            f"[{E}, {V}, {M}]"
        )
    if spec.version != meta.version:
        raise WhatIfError(
            f"spec targets version {spec.version!r}, the baseline is "
            f"{meta.version!r}"
        )
    W2, S2 = apply_arrays(scenario.weights, scenario.stakes, spec)
    config2 = apply_config(config, spec)
    k = spec.from_epoch

    resume = cache.resume_epoch(meta.key, k) if use_cache else 0
    state = None
    if resume > 0:
        try:
            state = cache.load_state(meta.key, resume)
        except Exception:
            # A torn/corrupt state artifact degrades to the full run —
            # a cache can slow a what-if down, never wrong or crash it.
            resume, state = 0, None
    cache_hit = state is not None

    def segment(lo: int, hi: int, cfg, carry, want_state: bool):
        seg = dc.replace(
            scenario,
            weights=W2[lo:hi],
            stakes=S2[lo:hi],
            num_epochs=hi - lo,
        )
        return simulate(
            seg,
            meta.version,
            cfg,
            save_bonds=False,
            save_incentives=True,
            epoch_impl=meta.engine,
            initial_state=carry,
            epoch_offset=lo,
            return_state=want_state,
        )

    parts_div, parts_inc = [], []
    if spec.hparams and k > resume:
        # Piecewise config: baseline config over [resume, k), the
        # perturbed config from k on (arrays before k are untouched by
        # construction, so this mid-segment re-simulates baseline bits).
        mid = segment(resume, k, config, state, True)
        parts_div.append(mid.dividends)
        parts_inc.append(mid.incentives)
        tail = segment(k, E, config2, mid.final_state, False)
        parts_div.append(tail.dividends)
        parts_inc.append(tail.incentives)
    else:
        tail = segment(resume, E, config2, state, False)
        parts_div.append(tail.dividends)
        parts_inc.append(tail.incentives)
    baseline = cache.load_baseline(meta.key)
    dividends = np.concatenate(
        [baseline["dividends"][:resume]] + parts_div
    )
    incentives = np.concatenate(
        [baseline["incentives"][:resume]] + parts_inc
    )

    if record and use_cache:
        if cache_hit:
            cache.record_hit(meta.key, resume_epoch=resume, total_epochs=E)
        else:
            cache.record_miss(
                meta.key,
                total_epochs=E,
                reason=(
                    "no_checkpoint_at_or_before_perturb_epoch"
                    if k < meta.stride
                    else "state_unavailable"
                ),
            )
    return WhatIfResult(
        spec=spec,
        dividends=dividends,
        incentives=incentives,
        dividend_delta=dividends - baseline["dividends"],
        incentive_delta=incentives - baseline["incentives"],
        cache_hit=cache_hit,
        resume_epoch=resume,
        epochs_simulated=E - resume,
        epochs_saved=resume,
        baseline_key=meta.key,
    )
