"""Bittensor metagraph snapshot ingestion: real subnets as Scenarios.

The documented snapshot schema (no chain client, no network — a
snapshot is a file an operator exports once and replays forever):

**JSON** (``*.json``)::

    {
      "format": "yuma-metagraph-v1",
      "netuid": 21,                  # subnet id (int)
      "block": 4_200_000,            # chain block the snapshot was read at
      "stakes": [.. V floats ..],    # raw TAO stake per validator
      "weights": [[.. M floats ..],  # dense row per validator, raw u16-scale
                  ...],              # or chain-normalized — rows are
    }                                # re-normalized on ingestion

**npz** (``*.npz``) — the bulk format for real-subnet shapes: arrays
``stakes [V] f32``, plus either dense ``weights [V, M] f32`` or the
sparse row triplet ``weights_indptr [V+1] i64`` / ``weights_indices
[nnz] i64`` / ``weights_values [nnz] f32`` (CSR — what a chain export
actually looks like: each validator weights a few dozen of 4096
miners), and scalars ``netuid`` / ``block``.

:func:`synthetic_snapshot` generates a deterministic snapshot at the
real-subnet flagship shape (V=256, M=4096 — the BENCH bucket and, since
0.16.0, a `tools/shapecheck.py` grid workload), so tests and CI
exercise the ingestion path and the full Yuma variant matrix with no
network and no checked-in 4-MB fixture. :func:`scenario_from_snapshot`
tiles a snapshot into the dense `Scenario` arrays every engine rung,
`plan_dispatch`, and the fleet/serve tiers consume unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import pathlib
from dataclasses import dataclass
from typing import Optional, Union

import numpy as np

from yuma_simulation_tpu.scenarios.base import Scenario
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)

FORMAT = "yuma-metagraph-v1"


class SnapshotError(ValueError):
    """A snapshot file that violates the documented schema."""


@dataclass(frozen=True)
class MetagraphSnapshot:
    """One subnet metagraph at one block: dense `[V, M]` weights +
    `[V]` stakes (raw scale; normalization happens at ingestion)."""

    netuid: int
    block: int
    stakes: np.ndarray  # [V] float32, raw (un-normalized) stake
    weights: np.ndarray  # [V, M] float32, raw weight rows

    def __post_init__(self):
        object.__setattr__(
            self, "stakes", np.asarray(self.stakes, np.float32)
        )
        object.__setattr__(
            self, "weights", np.asarray(self.weights, np.float32)
        )
        V = self.stakes.shape[0]
        if self.weights.ndim != 2 or self.weights.shape[0] != V:
            raise SnapshotError(
                f"weights {self.weights.shape} inconsistent with "
                f"stakes [{V}]"
            )

    @property
    def num_validators(self) -> int:
        return int(self.stakes.shape[0])

    @property
    def num_miners(self) -> int:
        return int(self.weights.shape[1])


def _check_snapshot(snap: MetagraphSnapshot) -> MetagraphSnapshot:
    if not np.isfinite(snap.weights).all() or (snap.weights < 0).any():
        raise SnapshotError(
            f"netuid {snap.netuid}: weights must be finite and "
            "non-negative"
        )
    if not np.isfinite(snap.stakes).all() or (snap.stakes < 0).any():
        raise SnapshotError(
            f"netuid {snap.netuid}: stakes must be finite and non-negative"
        )
    if snap.stakes.sum() <= 0:
        raise SnapshotError(f"netuid {snap.netuid}: zero total stake")
    return snap


# ------------------------------------------------------------------ load/save


def load_metagraph_snapshot(
    path: Union[str, pathlib.Path],
) -> MetagraphSnapshot:
    """Load a snapshot file (JSON or npz — see the module docstring for
    the schema) with full validation: a malformed or poisoned snapshot
    fails here as a typed :class:`SnapshotError`, never as NaNs in a
    consensus reduction."""
    path = pathlib.Path(path)
    if path.suffix == ".json":
        snap = _load_json(path)
    elif path.suffix == ".npz":
        snap = _load_npz(path)
    else:
        raise SnapshotError(
            f"unknown snapshot extension {path.suffix!r} (want .json/.npz)"
        )
    snap = _check_snapshot(snap)
    log_event(
        logger,
        "metagraph_loaded",
        level=logging.INFO,
        path=str(path),
        netuid=snap.netuid,
        block=snap.block,
        validators=snap.num_validators,
        miners=snap.num_miners,
    )
    return snap


def _load_json(path: pathlib.Path) -> MetagraphSnapshot:
    try:
        payload = json.loads(path.read_text(encoding="utf-8"))
    except json.JSONDecodeError as exc:
        raise SnapshotError(f"{path}: not valid JSON ({exc})") from None
    if payload.get("format") != FORMAT:
        raise SnapshotError(
            f"{path}: format={payload.get('format')!r}, want {FORMAT!r}"
        )
    for key in ("netuid", "block", "stakes", "weights"):
        if key not in payload:
            raise SnapshotError(f"{path}: missing key {key!r}")
    return MetagraphSnapshot(
        netuid=int(payload["netuid"]),
        block=int(payload["block"]),
        stakes=np.asarray(payload["stakes"], np.float32),
        weights=np.asarray(payload["weights"], np.float32),
    )


def _load_npz(path: pathlib.Path) -> MetagraphSnapshot:
    with np.load(path) as data:
        names = set(data.files)
        if "stakes" not in names:
            raise SnapshotError(f"{path}: missing 'stakes' array")
        stakes = np.asarray(data["stakes"], np.float32)
        if "weights" in names:
            weights = np.asarray(data["weights"], np.float32)
        elif {"weights_indptr", "weights_indices", "weights_values"} <= names:
            weights = _dense_from_csr(
                data["weights_indptr"],
                data["weights_indices"],
                data["weights_values"],
                num_validators=stakes.shape[0],
                num_miners=int(data["num_miners"])
                if "num_miners" in names
                else None,
            )
        else:
            raise SnapshotError(
                f"{path}: need 'weights' or the CSR triplet "
                "'weights_indptr'/'weights_indices'/'weights_values'"
            )
        return MetagraphSnapshot(
            netuid=int(data["netuid"]) if "netuid" in names else 0,
            block=int(data["block"]) if "block" in names else 0,
            stakes=stakes,
            weights=weights,
        )


def _dense_from_csr(
    indptr, indices, values, *, num_validators: int, num_miners: Optional[int]
) -> np.ndarray:
    indptr = np.asarray(indptr, np.int64)
    indices = np.asarray(indices, np.int64)
    values = np.asarray(values, np.float32)
    if indptr.shape != (num_validators + 1,):
        raise SnapshotError(
            f"weights_indptr shape {indptr.shape} != [V+1]="
            f"[{num_validators + 1}]"
        )
    if indices.shape != values.shape:
        raise SnapshotError("weights_indices/values length mismatch")
    M = int(num_miners) if num_miners else int(indices.max(initial=-1)) + 1
    if indices.size and (
        int(indices.min()) < 0 or int(indices.max()) >= max(M, 1)
    ):
        # A negative index would silently wrap onto the LAST miner
        # column; an oversized one would crash as a raw IndexError —
        # both must surface as the typed schema error the loader
        # promises.
        raise SnapshotError(
            f"weights_indices out of range [0, {M}): "
            f"min={int(indices.min())}, max={int(indices.max())}"
        )
    W = np.zeros((num_validators, max(M, 1)), np.float32)
    for v in range(num_validators):
        lo, hi = int(indptr[v]), int(indptr[v + 1])
        W[v, indices[lo:hi]] = values[lo:hi]
    return W


def save_metagraph_snapshot(
    snap: MetagraphSnapshot,
    path: Union[str, pathlib.Path],
    *,
    sparse: bool = True,
) -> pathlib.Path:
    """Write a snapshot in the documented schema (the format
    round-trips bitwise — pinned by tests). JSON writes dense rows;
    npz writes CSR when `sparse` (the realistic export: a few dozen
    non-zeros per 4096-wide row) else dense."""
    path = pathlib.Path(path)
    _check_snapshot(snap)
    if path.suffix == ".json":
        payload = {
            "format": FORMAT,
            "netuid": snap.netuid,
            "block": snap.block,
            "stakes": [float(s) for s in snap.stakes],
            "weights": [[float(w) for w in row] for row in snap.weights],
        }
        path.write_text(json.dumps(payload), encoding="utf-8")
        return path
    if path.suffix != ".npz":
        raise SnapshotError(
            f"unknown snapshot extension {path.suffix!r} (want .json/.npz)"
        )
    if sparse:
        indptr = [0]
        indices: list = []
        values: list = []
        for row in snap.weights:
            (nz,) = np.nonzero(row)
            indices.extend(int(i) for i in nz)
            values.extend(row[nz])
            indptr.append(len(indices))
        np.savez_compressed(
            path,
            netuid=snap.netuid,
            block=snap.block,
            stakes=snap.stakes,
            weights_indptr=np.asarray(indptr, np.int64),
            weights_indices=np.asarray(indices, np.int64),
            weights_values=np.asarray(values, np.float32),
            num_miners=snap.num_miners,
        )
    else:
        np.savez_compressed(
            path,
            netuid=snap.netuid,
            block=snap.block,
            stakes=snap.stakes,
            weights=snap.weights,
        )
    return path


# ------------------------------------------------------------------ synthesis


def synthetic_snapshot(
    seed: int,
    *,
    num_validators: int = 256,
    num_miners: int = 4096,
    nnz_per_row: int = 48,
    stake_tail: float = 1.2,
    consensus_sharpness: float = 8.0,
    netuid: int = 0,
    block: int = 0,
) -> MetagraphSnapshot:
    """A deterministic snapshot at real-subnet shape (default V=256,
    M=4096 — the BENCH flagship bucket), statistically subnet-shaped:

    - stakes are heavy-tailed (Pareto-ish via lognormal, `stake_tail`
      controlling dispersion) — a few whales, a long tail;
    - a shared "consensus" miner-quality vector (Dirichlet-like via
      Gamma draws, `consensus_sharpness` concentrating mass on few
      miners) that every validator's row follows with individual noise;
    - each row touches only `nnz_per_row` miners (chain reality: u16
      weight slots are scarce), sampled by consensus quality.

    Pure numpy on an explicit `default_rng(seed)` — bitwise
    reproducible anywhere, so CI needs no network and no fixture blob.
    """
    rng = np.random.default_rng(seed)
    stakes = rng.lognormal(
        mean=0.0, sigma=stake_tail, size=num_validators
    ).astype(np.float32)
    quality = rng.gamma(
        1.0 / consensus_sharpness, size=num_miners
    ).astype(np.float64)
    quality /= quality.sum()
    W = np.zeros((num_validators, num_miners), np.float32)
    nnz = min(nnz_per_row, num_miners)
    for v in range(num_validators):
        chosen = rng.choice(num_miners, size=nnz, replace=False, p=quality)
        noise = rng.lognormal(mean=0.0, sigma=0.35, size=nnz)
        row = quality[chosen] * noise
        W[v, chosen] = (row / row.sum()).astype(np.float32)
    return _check_snapshot(
        MetagraphSnapshot(
            netuid=netuid, block=block, stakes=stakes, weights=W
        )
    )


# ------------------------------------------------------------------ ingestion


def scenario_from_snapshot(
    snap: MetagraphSnapshot,
    *,
    num_epochs: int = 40,
    name: Optional[str] = None,
) -> Scenario:
    """Tile a snapshot into the dense `Scenario` every engine rung and
    `plan_dispatch` consume: weight rows re-normalized (zero rows stay
    zero), stakes normalized to fractions, both held constant across
    `num_epochs` (replaying a snapshot SEQUENCE as an epoch-varying
    scenario is the chain-replay service's job, ROADMAP item 5).
    Validated on the way out — row-normalized, finite, non-negative."""
    row_sums = snap.weights.sum(axis=1, keepdims=True)
    W_n = np.divide(
        snap.weights,
        row_sums,
        out=np.zeros_like(snap.weights),
        where=row_sums > 0,
    ).astype(np.float32)
    S_n = (snap.stakes / snap.stakes.sum()).astype(np.float32)
    V = snap.num_validators
    validators = [f"uid {v} ({S_n[v]:.4f})" for v in range(V)]
    scenario = Scenario(
        name=name
        or (
            f"metagraph netuid={snap.netuid} block={snap.block} "
            f"({V}x{snap.num_miners})"
        ),
        validators=validators,
        base_validator=validators[int(np.argmax(S_n))],
        weights=np.tile(W_n[None], (num_epochs, 1, 1)),
        stakes=np.tile(S_n[None], (num_epochs, 1)),
        num_epochs=num_epochs,
        servers=[f"Server {m + 1}" for m in range(snap.num_miners)],
    )
    scenario.validate(normalized=True)
    from yuma_simulation_tpu.foundry.dsl import record_scenario_generated

    record_scenario_generated()
    return scenario


def snapshot_to_dict(snap: MetagraphSnapshot) -> dict:
    """JSON-able form (the `.json` schema) — symmetric with
    :func:`load_metagraph_snapshot` for tests and tooling."""
    return {
        "format": FORMAT,
        **{
            k: (v.tolist() if isinstance(v, np.ndarray) else v)
            for k, v in dataclasses.asdict(snap).items()
        },
    }
