"""The generated-suite supervisor drill: the CI scenario lane's engine.

``python -m yuma_simulation_tpu.foundry --drill --bundle-dir DIR`` draws
a seeded Monte-Carlo population from the adversarial families (copiers,
cartels, churn shocks, takeovers — every draw a serializable DSL spec),
runs it through the full supervised tier (`SweepSupervisor.run_batch`,
donor-packed, 100% numerics canaries) into a flight-recorder bundle at
DIR, and exits non-zero on quarantined lanes or confirmed drift. CI
then gates the bundle with ``obsreport --check`` (every ledger record
resolves to a span, counts reconcile) and ``driftreport --check
--require`` (primary/canary fingerprints bitwise identical) — the same
gates every other drill bundle passes.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence


def build_drill_suite(seed: int, size: int):
    """The drill population: a deterministic rotation over the four
    adversarial families, each draw's parameters derived from (seed,
    index). Same (seed, size) -> bitwise-identical suite on any host."""
    from yuma_simulation_tpu.foundry.adversarial import (
        cartel_scenario,
        stake_churn_scenario,
        takeover_scenario,
        weight_copier_scenario,
    )
    from yuma_simulation_tpu.foundry.montecarlo import derived_seed

    families = (
        lambda s: weight_copier_scenario(s, num_miners=4, num_epochs=16),
        lambda s: cartel_scenario(s, num_miners=4, num_epochs=16),
        lambda s: stake_churn_scenario(
            s, num_validators=3, num_miners=4, num_epochs=16
        ),
        lambda s: takeover_scenario(s, num_miners=4, num_epochs=16),
    )
    return [
        families[i % len(families)](derived_seed(seed, i)).scenario
        for i in range(size)
    ]


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m yuma_simulation_tpu.foundry",
        description=__doc__.split("\n\n")[0],
    )
    parser.add_argument(
        "--drill",
        action="store_true",
        help="run the generated-suite supervisor drill (CI smoke; "
        "forces the CPU backend)",
    )
    parser.add_argument(
        "--bundle-dir",
        default="foundry-bundle",
        help="flight-bundle directory the drill publishes into",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--suite-size", type=int, default=8,
        help="generated scenarios in the drill population",
    )
    parser.add_argument(
        "--version", default="Yuma 1 (paper)",
        help="Yuma version the drill sweeps",
    )
    args = parser.parse_args(argv)
    if not args.drill:
        parser.print_help()
        return 2

    import pathlib

    target = pathlib.Path(args.bundle_dir)
    if target.exists() and any(target.iterdir()):
        # A resumed drill satisfies units from the prior run's chunks
        # and generates nothing — refuse, like obsreport --drill does.
        print(
            f"--bundle-dir {args.bundle_dir!r} exists and is not empty; "
            "point the drill at a fresh directory",
            file=sys.stderr,
        )
        return 2

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from yuma_simulation_tpu.resilience.supervisor import SweepSupervisor
    from yuma_simulation_tpu.utils import setup_logging

    setup_logging()
    suite = build_drill_suite(args.seed, args.suite_size)
    supervisor = SweepSupervisor(
        directory=args.bundle_dir,
        unit_size=2,
        canary_fraction=1.0,
    )
    out = supervisor.run_batch(
        suite, args.version, pack=True, tag="foundry_drill"
    )
    report = out["report"]
    quarantined = len(out["quarantine"].entries)
    print(
        f"foundry drill complete: {len(suite)} generated scenarios "
        f"(seed={args.seed}) units_completed={report.units_completed} "
        f"canaries={report.canaries_run} drift={report.drift_events} "
        f"quarantined={quarantined}"
    )
    return 1 if (quarantined or report.drift_events) else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
