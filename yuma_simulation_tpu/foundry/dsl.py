"""The declarative scenario DSL: composable primitives -> dense arrays.

Every workload the platform carries — dispatch plans, donor packing,
numerics capture, all three engine rungs — consumes one representation:
the dense `Scenario` arrays (`weights[E, V, M]` / `stakes[E, V]`,
scenarios/base.py). This module is the generator side of that contract:
small frozen *primitives* (stake trajectories, weight schedules, epoch
events) are combined by a tiny combinator algebra (:func:`sequence`,
:func:`overlay`, :func:`at_epochs`) into a frozen, serializable
:class:`ScenarioSpec`, and :func:`compile_spec` materializes the spec
deterministically into exactly the arrays the hand-written builders in
`scenarios/builtin.py` produce — pinned bitwise by
tests/unit/test_foundry_dsl.py for the re-expressed built-in cases.

Compilation order is part of the contract: stake clauses first (in
clause order, later writes win on overlap), then weight clauses (a
:class:`CopyWithLag` or :class:`NoisyConsensusFollower` clause reads the
rows earlier clauses already painted), then events
(:class:`Takeover` rescales stakes; :class:`BondReset` becomes scenario
metadata). Everything is host-side numpy with explicit integer seeds —
two compiles of one spec are bitwise identical on any machine.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from dataclasses import dataclass
from typing import Optional, Union

import numpy as np

from yuma_simulation_tpu.scenarios.base import Scenario
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)


class SpecError(ValueError):
    """A spec that cannot compile (bad indices, shape mismatches)."""


def _check_validator(num_validators: int, index: int, label: str) -> None:
    """Typed bounds check for validator indices carried by primitives:
    a negative index would silently numpy-wrap onto another validator's
    row, an oversized one would escape as a raw IndexError — both must
    be the DSL's own SpecError (this is a serializable public surface)."""
    if not 0 <= int(index) < num_validators:
        raise SpecError(
            f"{label}={index} out of range for {num_validators} validators"
        )


def record_scenario_generated() -> None:
    """The ONE increment site wrapper for the `scenarios_generated`
    counter — every foundry generator (DSL compiles, snapshot
    ingestion) counts through here, and the help text is read from the
    registry's declaration rather than re-typed."""
    from yuma_simulation_tpu.telemetry.metrics import get_registry
    from yuma_simulation_tpu.telemetry.registry import METRICS

    get_registry().counter(
        "scenarios_generated", METRICS["scenarios_generated"].summary
    ).inc()


# --------------------------------------------------------------- primitives
#
# Each primitive is a frozen dataclass whose fields are plain JSON-able
# scalars/tuples (the serialization contract), with one `paint` method
# mutating the dense array slice `[lo:hi]` it is clause-scoped to.


@dataclass(frozen=True)
class OneHot:
    """One-hot weight assignment: validator v puts full weight on miner
    `assignments[v]` — the `assignment_weights` schedule rule."""

    assignments: tuple

    def paint(self, W: np.ndarray, S: np.ndarray, lo: int, hi: int) -> None:
        if len(self.assignments) != W.shape[1]:
            raise SpecError(
                f"OneHot names {len(self.assignments)} validators, "
                f"spec has {W.shape[1]}"
            )
        W[lo:hi] = 0.0
        for v, m in enumerate(self.assignments):
            if not 0 <= int(m) < W.shape[2]:
                raise SpecError(
                    f"OneHot assigns validator {v} to miner {m}, spec has "
                    f"{W.shape[2]} miners"
                )
            W[lo:hi, v, int(m)] = 1.0


@dataclass(frozen=True)
class Rows:
    """Explicit per-validator weight rows — the `row_weights` rule."""

    rows: tuple  # tuple[tuple[float, ...], ...] of shape [V, M]

    def paint(self, W: np.ndarray, S: np.ndarray, lo: int, hi: int) -> None:
        mat = np.asarray(self.rows, np.float32)
        if mat.shape != W.shape[1:]:
            raise SpecError(
                f"Rows shape {mat.shape} != spec's (V, M) {W.shape[1:]}"
            )
        W[lo:hi] = mat


@dataclass(frozen=True)
class CopyWithLag:
    """Weight copying: validator `dst` reproduces validator `src`'s row
    from `lag` epochs earlier (clamped at the scenario start) — the
    canonical weight-copier adversary. Reads the rows earlier clauses
    already painted, so sequence it AFTER the honest schedule."""

    dst: int
    src: int
    lag: int = 1

    def paint(self, W: np.ndarray, S: np.ndarray, lo: int, hi: int) -> None:
        if self.lag < 0:
            raise SpecError(f"CopyWithLag lag must be >= 0, got {self.lag}")
        _check_validator(W.shape[1], self.dst, "CopyWithLag.dst")
        _check_validator(W.shape[1], self.src, "CopyWithLag.src")
        for e in range(lo, hi):
            W[e, self.dst] = W[max(e - self.lag, 0), self.src]


@dataclass(frozen=True)
class NoisyConsensusFollower:
    """Validator `validator` follows the stake-weighted mean of every
    OTHER validator's current row, perturbed by multiplicative
    log-normal noise (sigma) and re-normalized. Deterministic: the RNG
    is seeded per (seed, epoch), never from global state."""

    validator: int
    sigma: float = 0.05
    seed: int = 0

    def paint(self, W: np.ndarray, S: np.ndarray, lo: int, hi: int) -> None:
        v = self.validator
        _check_validator(W.shape[1], v, "NoisyConsensusFollower.validator")
        others = [i for i in range(W.shape[1]) if i != v]
        if not others:
            raise SpecError("NoisyConsensusFollower needs >= 2 validators")
        for e in range(lo, hi):
            stakes = S[e, others]
            total = stakes.sum()
            share = (
                stakes / total
                if total > 0
                else np.full(len(others), 1.0 / len(others), np.float32)
            )
            consensus = (share[:, None] * W[e, others]).sum(axis=0)
            rng = np.random.default_rng((self.seed, e))
            noisy = consensus * np.exp(
                self.sigma * rng.standard_normal(consensus.shape)
            ).astype(np.float32)
            row_sum = noisy.sum()
            W[e, v] = (noisy / row_sum if row_sum > 0 else noisy).astype(
                np.float32
            )


@dataclass(frozen=True)
class Stakes:
    """Constant stakes over the clause's epoch range. With
    :func:`at_epochs` this is also the churn-shock / join / leave
    trajectory: a later clause stepping to new values (zeros = left)."""

    values: tuple

    def paint(self, W: np.ndarray, S: np.ndarray, lo: int, hi: int) -> None:
        vals = np.asarray(self.values, np.float32)
        if vals.shape != (S.shape[1],):
            raise SpecError(
                f"Stakes names {vals.shape[0]} validators, spec has "
                f"{S.shape[1]}"
            )
        S[lo:hi] = vals


@dataclass(frozen=True)
class StakeDrift:
    """Linear per-validator stake drift from `start_values` to
    `end_values` across the clause's epoch range (endpoints inclusive)."""

    start_values: tuple
    end_values: tuple

    def paint(self, W: np.ndarray, S: np.ndarray, lo: int, hi: int) -> None:
        a = np.asarray(self.start_values, np.float32)
        b = np.asarray(self.end_values, np.float32)
        if a.shape != (S.shape[1],) or b.shape != (S.shape[1],):
            raise SpecError("StakeDrift endpoint length != num validators")
        span = max(hi - lo - 1, 1)
        for e in range(lo, hi):
            t = np.float32((e - lo) / span)
            S[e] = a + t * (b - a)


@dataclass(frozen=True)
class BondReset:
    """Epoch event: the case's bond-reset metadata (reference cases with
    `reset_bonds`): validator `index` resets at `epoch`."""

    index: int
    epoch: int


@dataclass(frozen=True)
class Takeover:
    """Epoch event: validator `validator` seizes `stake_fraction` of the
    subnet stake from `epoch` on; every other validator's stake is
    scaled down proportionally so the per-epoch total is preserved."""

    validator: int
    epoch: int
    stake_fraction: float = 0.6

    def paint(self, W: np.ndarray, S: np.ndarray, lo: int, hi: int) -> None:
        del lo, hi
        v = self.validator
        _check_validator(S.shape[1], v, "Takeover.validator")
        if not 0.0 < self.stake_fraction < 1.0:
            raise SpecError(
                f"Takeover stake_fraction must be in (0, 1), got "
                f"{self.stake_fraction}"
            )
        if not 0 <= self.epoch < S.shape[0]:
            raise SpecError(
                f"Takeover.epoch={self.epoch} out of range for "
                f"{S.shape[0]} epochs"
            )
        for e in range(self.epoch, S.shape[0]):
            total = S[e].sum()
            others = total - S[e, v]
            if total <= 0:
                continue
            if others <= 0:
                # v already holds ALL stake: there is nobody to seize
                # from, and rescaling would shrink the per-epoch total
                # the docstring promises to preserve — leave the epoch
                # untouched.
                continue
            scale = (1.0 - self.stake_fraction) * total / others
            S[e] *= np.float32(scale)
            S[e, v] = np.float32(self.stake_fraction) * total


#: The serialization registry: type tag -> primitive class. Every
#: primitive above must be listed or `spec_from_dict` cannot round-trip.
PRIMITIVES = {
    cls.__name__: cls
    for cls in (
        OneHot,
        Rows,
        CopyWithLag,
        NoisyConsensusFollower,
        Stakes,
        StakeDrift,
        BondReset,
        Takeover,
    )
}

WeightPrim = Union[OneHot, Rows, CopyWithLag, NoisyConsensusFollower]
StakePrim = Union[Stakes, StakeDrift]
EventPrim = Union[BondReset, Takeover]


# ------------------------------------------------------------- combinators


@dataclass(frozen=True)
class Clause:
    """One primitive scoped to the epoch range `[start, stop)`;
    `stop=None` means "to the end of the scenario"."""

    prim: object
    start: int = 0
    stop: Optional[int] = None

    def bounds(self, num_epochs: int) -> tuple[int, int]:
        stop = num_epochs if self.stop is None else min(self.stop, num_epochs)
        lo = max(int(self.start), 0)
        return lo, max(stop, lo)


def at_epochs(prim, start: int, stop: Optional[int] = None) -> Clause:
    """Scope a primitive to `[start, stop)` epochs (later clauses win on
    overlap, exactly like the builtin schedules' range rules)."""
    if isinstance(prim, Clause):
        raise SpecError("at_epochs takes a primitive, not a Clause")
    return Clause(prim, start, stop)


def sequence(*items) -> tuple:
    """Normalize primitives/clauses into an ordered clause tuple; bare
    primitives cover the whole scenario. Order is application order —
    the last writer of an epoch wins."""
    out = []
    for item in items:
        out.append(item if isinstance(item, Clause) else Clause(item))
    return tuple(out)


def overlay(*programs) -> tuple:
    """Concatenate clause programs; the later program paints on top of
    (and may read the state left by) the earlier one."""
    out: list = []
    for prog in programs:
        if isinstance(prog, (Clause,)) or not isinstance(prog, (tuple, list)):
            out.extend(sequence(prog))
        else:
            out.extend(sequence(*prog))
    return tuple(out)


# ---------------------------------------------------------------- the spec


@dataclass(frozen=True)
class ScenarioSpec:
    """A frozen, serializable scenario program.

    `weights` / `stakes` are clause tuples (see :func:`sequence`);
    `events` holds :class:`BondReset` / :class:`Takeover` primitives
    (unscoped — each carries its own epoch). `servers=None` derives the
    reference's "Server i" naming from `num_miners`."""

    name: str
    validators: tuple
    base_validator: str
    num_miners: int
    num_epochs: int = 40
    weights: tuple = ()
    stakes: tuple = ()
    events: tuple = ()
    servers: Optional[tuple] = None
    plot_incentives: bool = False

    def __post_init__(self):
        if self.base_validator not in self.validators:
            raise SpecError(
                f"base_validator {self.base_validator!r} not among "
                f"validators {self.validators!r}"
            )
        if self.num_miners < 1 or self.num_epochs < 1:
            raise SpecError("num_miners and num_epochs must be >= 1")


def compile_spec(spec: ScenarioSpec, *, validate: bool = True) -> Scenario:
    """Materialize a :class:`ScenarioSpec` into dense `Scenario` arrays.

    Deterministic (two compiles are bitwise identical) and validated on
    the way out (:meth:`..scenarios.base.Scenario.validate` — a spec
    whose program paints NaN/negative weights fails here, not three
    layers down in an engine reduction). Every downstream consumer —
    `plan_dispatch`, donor packing, the engine rungs, the fleet/serve
    tiers — takes the result unchanged."""
    V = len(spec.validators)
    E, M = spec.num_epochs, spec.num_miners
    W = np.zeros((E, V, M), np.float32)
    S = np.zeros((E, V), np.float32)

    for clause in sequence(*spec.stakes):
        lo, hi = clause.bounds(E)
        clause.prim.paint(W, S, lo, hi)
    for clause in sequence(*spec.weights):
        lo, hi = clause.bounds(E)
        clause.prim.paint(W, S, lo, hi)

    reset_index = reset_epoch = None
    for event in spec.events:
        if isinstance(event, BondReset):
            if reset_index is not None:
                # Scenario carries exactly one reset; accepting two and
                # keeping the last would silently simulate a different
                # spec than the one serialized.
                raise SpecError(
                    f"spec {spec.name!r} declares more than one "
                    "BondReset; Scenario supports at most one"
                )
            _check_validator(V, event.index, "BondReset.index")
            if not 0 <= int(event.epoch) < E:
                raise SpecError(
                    f"BondReset.epoch={event.epoch} out of range for "
                    f"{E} epochs"
                )
            reset_index, reset_epoch = int(event.index), int(event.epoch)
        elif isinstance(event, Takeover):
            event.paint(W, S, 0, E)
        else:
            raise SpecError(f"unknown event primitive {event!r}")

    scenario = Scenario(
        name=spec.name,
        validators=list(spec.validators),
        base_validator=spec.base_validator,
        weights=W,
        stakes=S,
        num_epochs=E,
        reset_bonds=reset_index is not None,
        reset_bonds_index=reset_index,
        reset_bonds_epoch=reset_epoch,
        servers=(
            list(spec.servers)
            if spec.servers is not None
            else [f"Server {i + 1}" for i in range(M)]
        ),
        plot_incentives=spec.plot_incentives,
    )
    if validate:
        # DSL rows are normalized by construction (one-hot assignments,
        # normalized Rows, renormalized followers) — enforce it, so a
        # mis-entered Rows matrix fails at compile with provenance.
        scenario.validate(normalized=True)
    record_scenario_generated()
    log_event(
        logger,
        "scenario_compiled",
        level=logging.DEBUG,
        name=spec.name,
        epochs=E,
        validators=V,
        miners=M,
        clauses=len(spec.weights) + len(spec.stakes) + len(spec.events),
    )
    return scenario


# ------------------------------------------------------------ serialization


def _prim_to_dict(prim) -> dict:
    return {"type": type(prim).__name__, **dataclasses.asdict(prim)}


def _prim_from_dict(payload: dict):
    kind = payload.get("type")
    cls = PRIMITIVES.get(kind)
    if cls is None:
        raise SpecError(f"unknown primitive type {kind!r}")
    kwargs = {k: v for k, v in payload.items() if k != "type"}
    for field in dataclasses.fields(cls):
        if field.name in kwargs and isinstance(kwargs[field.name], list):
            kwargs[field.name] = _tupleize(kwargs[field.name])
    return cls(**kwargs)


def _tupleize(value):
    if isinstance(value, list):
        return tuple(_tupleize(v) for v in value)
    return value


def _clause_to_dict(clause: Clause) -> dict:
    return {
        "prim": _prim_to_dict(clause.prim),
        "start": clause.start,
        "stop": clause.stop,
    }


def spec_to_dict(spec: ScenarioSpec) -> dict:
    """The JSON-able form of a spec — the wire/disk format of the
    foundry (suite manifests, serve payload keys, CI artifacts)."""
    return {
        "format": "yuma-scenario-spec-v1",
        "name": spec.name,
        "validators": list(spec.validators),
        "base_validator": spec.base_validator,
        "num_miners": spec.num_miners,
        "num_epochs": spec.num_epochs,
        "weights": [_clause_to_dict(c) for c in sequence(*spec.weights)],
        "stakes": [_clause_to_dict(c) for c in sequence(*spec.stakes)],
        "events": [_prim_to_dict(e) for e in spec.events],
        "servers": None if spec.servers is None else list(spec.servers),
        "plot_incentives": spec.plot_incentives,
    }


def spec_from_dict(payload: dict) -> ScenarioSpec:
    """Inverse of :func:`spec_to_dict`; compiles bitwise-identically.
    Malformed payloads (missing keys included) raise the DSL's typed
    :class:`SpecError`, never a bare KeyError — this is the wire
    format's parse boundary."""
    if payload.get("format") != "yuma-scenario-spec-v1":
        raise SpecError(
            f"not a scenario-spec payload (format={payload.get('format')!r})"
        )
    try:
        return ScenarioSpec(
            name=payload["name"],
            validators=tuple(payload["validators"]),
            base_validator=payload["base_validator"],
            num_miners=int(payload["num_miners"]),
            num_epochs=int(payload["num_epochs"]),
            weights=tuple(
                Clause(_prim_from_dict(c["prim"]), c["start"], c["stop"])
                for c in payload.get("weights", ())
            ),
            stakes=tuple(
                Clause(_prim_from_dict(c["prim"]), c["start"], c["stop"])
                for c in payload.get("stakes", ())
            ),
            events=tuple(
                _prim_from_dict(e) for e in payload.get("events", ())
            ),
            servers=(
                None
                if payload.get("servers") is None
                else tuple(payload["servers"])
            ),
            plot_incentives=bool(payload.get("plot_incentives", False)),
        )
    except (KeyError, TypeError) as exc:
        raise SpecError(
            f"malformed scenario-spec payload: {type(exc).__name__}: {exc}"
        ) from None


def spec_to_json(spec: ScenarioSpec) -> str:
    return json.dumps(spec_to_dict(spec), sort_keys=True)


def spec_from_json(text: str) -> ScenarioSpec:
    return spec_from_dict(json.loads(text))


def spec_key(spec: ScenarioSpec) -> str:
    """A short deterministic content key for a spec (suite manifests,
    serve-tier request keys): sha256 of the canonical JSON form."""
    import hashlib

    return hashlib.sha256(spec_to_json(spec).encode("utf-8")).hexdigest()[:16]


# ------------------------------------- built-in cases, re-expressed (pin)

_DEFAULT_STAKES = (0.8, 0.1, 0.1)


def builtin_case_specs() -> dict:
    """Six of the 14 built-in cases re-expressed in the DSL.

    tests/unit/test_foundry_dsl.py pins each compile BITWISE against the
    hand-built arrays in `scenarios/builtin.py` — the proof that the DSL
    reaches the exact representation the rest of the platform is pinned
    on (goldens, donor packing, drift canaries), not an approximation."""
    specs = {}
    specs["Case 1"] = ScenarioSpec(
        name="Case 1 - kappa moves first",
        validators=(
            "Big vali. (0.8)",
            "Small lazy vali. (0.1)",
            "Small lazier vali. (0.1)",
        ),
        base_validator="Big vali. (0.8)",
        num_miners=2,
        stakes=sequence(Stakes(_DEFAULT_STAKES)),
        weights=sequence(
            at_epochs(OneHot((0, 0, 0)), 0, 1),
            at_epochs(OneHot((1, 0, 0)), 1, 2),
            at_epochs(OneHot((1, 1, 0)), 2, 3),
            at_epochs(OneHot((1, 1, 1)), 3),
        ),
    )
    specs["Case 2"] = ScenarioSpec(
        name="Case 2 - kappa moves second",
        validators=(
            "Big vali. (0.8)",
            "Small eager vali. (0.1)",
            "Small lazy vali. (0.1)",
        ),
        base_validator="Small eager vali. (0.1)",
        num_miners=2,
        stakes=sequence(Stakes(_DEFAULT_STAKES)),
        weights=sequence(
            at_epochs(OneHot((0, 0, 0)), 0, 1),
            at_epochs(OneHot((0, 1, 0)), 1, 2),
            at_epochs(OneHot((1, 1, 0)), 2, 3),
            at_epochs(OneHot((1, 1, 1)), 3),
        ),
    )
    specs["Case 3"] = ScenarioSpec(
        name="Case 3 - kappa moves third",
        validators=(
            "Big vali. (0.8)",
            "Small eager vali. (0.1)",
            "Small lazy vali. (0.1)",
        ),
        base_validator="Small eager vali. (0.1)",
        num_miners=2,
        stakes=sequence(Stakes(_DEFAULT_STAKES)),
        weights=sequence(
            at_epochs(OneHot((0, 0, 0)), 0, 1),
            at_epochs(OneHot((0, 1, 0)), 1, 2),
            at_epochs(OneHot((0, 1, 1)), 2, 3),
            at_epochs(OneHot((1, 1, 1)), 3),
        ),
    )
    specs["Case 4"] = ScenarioSpec(
        name="Case 4 - all validators switch",
        validators=(
            "Big vali. (0.8)",
            "Small vali. (0.1)",
            "Small vali 2. (0.1)",
        ),
        base_validator="Big vali. (0.8)",
        num_miners=2,
        stakes=sequence(Stakes(_DEFAULT_STAKES)),
        weights=sequence(
            at_epochs(OneHot((0, 0, 0)), 0, 1),
            at_epochs(OneHot((1, 1, 1)), 1),
        ),
    )
    specs["Case 9"] = ScenarioSpec(
        name="Case 9 - small validators merged in e5",
        validators=(
            "Big vali. (0.8)",
            "Small vali. (0.1/0.2)",
            "Small vali 2. (0.1/0.0)",
        ),
        base_validator="Big vali. (0.8)",
        num_miners=2,
        stakes=sequence(
            Stakes(_DEFAULT_STAKES),
            at_epochs(Stakes((0.8, 0.2, 0.0)), 6),
        ),
        weights=sequence(OneHot((1, 1, 1))),
    )
    specs["Case 14"] = ScenarioSpec(
        name=(
            "Case 14 - All validators support Server 1, one of them "
            "switches to Server 2 for one epoch"
        ),
        validators=("Vali. 1 (0.33)", "Vali. 2 (0.33)", "Vali. 3 (0.34)"),
        base_validator="Vali. 1 (0.33)",
        num_miners=2,
        stakes=sequence(Stakes((0.33, 0.33, 0.34))),
        weights=sequence(
            at_epochs(OneHot((0, 0, 0)), 0, 20),
            at_epochs(OneHot((0, 0, 1)), 20, 21),
            at_epochs(OneHot((0, 0, 0)), 21),
        ),
    )
    return specs
