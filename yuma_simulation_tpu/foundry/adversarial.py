"""Adversarial scenario families with property-gated dividends.

Four parameterized generators — weight copying, collusion cartels,
stake-churn shocks, validator takeover — each built ON the DSL
(:mod:`.dsl` primitives, so every adversary is a serializable
:class:`~.dsl.ScenarioSpec` first and dense arrays second) and each
paired with a property assertion about dividend outcomes:

- a **lag-k weight copier** earns strictly less than the validator it
  copies under liquid alpha (the mechanism the liquid-alpha family
  exists to enforce — PAPER.md);
- a **cartel** whose stake fraction sits below the consensus majority
  (kappa) earns its self-dealt miner no consensus weight beyond the u16
  quantization floor, so the cartel miner's incentive is bounded at
  grid-step level (vs ~1.0/epoch once the cartel holds the majority);
- a **takeover** validator's dividend share rises only after the
  takeover epoch;
- a **churn shock** never breaks the per-epoch dividend normalization.

The assertion helpers (:func:`total_dividends`,
:func:`copier_dividend_gap`, :func:`cartel_miner_incentive`) are plain
functions so the property suite (tests/unit/test_foundry_properties.py)
and operator notebooks share one implementation. All randomness flows
from explicit integer seeds through `np.random.default_rng` — a failing
property reproduces from its seed alone.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from yuma_simulation_tpu.foundry.dsl import (
    BondReset,
    CopyWithLag,
    OneHot,
    Rows,
    ScenarioSpec,
    Stakes,
    Takeover,
    at_epochs,
    compile_spec,
    sequence,
)
from yuma_simulation_tpu.models.config import YumaConfig, YumaParams
from yuma_simulation_tpu.models.variants import YUMA_VERSIONS
from yuma_simulation_tpu.scenarios.base import Scenario

#: Versions where `liquid_alpha=True` changes the bond recurrence (the
#: EMA families and relative bonds; the capacity family ignores it —
#: models/epoch.py gates on `bonds_mode is not CAPACITY`). The copier
#: property quantifies over exactly this set.
LIQUID_ALPHA_VERSIONS = tuple(
    name
    for name, spec in YUMA_VERSIONS.items()
    if spec.bonds_mode.value not in ("capacity",)
)

#: The sub-majority cartel bound: a clipped column can still carry up to
#: a couple of u16 consensus grid steps (1/65535 each — quantization
#: floor, not economics), so "the cartel earns nothing" is asserted as
#: per-epoch incentive <= this. Majority capture sits ~5 orders of
#: magnitude above it (~1.0/epoch).
CARTEL_INCENTIVE_FLOOR_PER_EPOCH = 2.0 / 65535.0


@dataclass(frozen=True)
class AdversarialScenario:
    """One generated adversary: the compiled scenario, its spec, and
    the role indices the property assertions quantify over."""

    scenario: Scenario
    spec: ScenarioSpec
    roles: dict  # role name -> validator (or miner) index


def _segments(rng: np.random.Generator, num_epochs: int, num_segments: int):
    """Random honest-schedule segmentation: `num_segments` epoch spans
    covering [0, num_epochs), each >= 3 epochs so bonds have time to
    move inside every segment."""
    candidates = np.arange(3, num_epochs - 2, 3)
    if num_segments < 1 or len(candidates) < num_segments - 1:
        # Surface the real constraint instead of numpy's opaque
        # "Cannot take a larger sample" — this is a public-surface
        # builder fed by Monte-Carlo draws.
        from yuma_simulation_tpu.foundry.dsl import SpecError

        raise SpecError(
            f"num_epochs={num_epochs} is too short for "
            f"{num_segments} schedule segments (needs num_epochs >= "
            f"{3 * num_segments})"
        )
    cuts = sorted(
        rng.choice(
            candidates, size=num_segments - 1, replace=False
        ).tolist()
    )
    bounds = [0, *cuts, num_epochs]
    return list(zip(bounds[:-1], bounds[1:]))


def weight_copier_scenario(
    seed: int = 0,
    *,
    num_miners: int = 4,
    num_epochs: int = 36,
    lag: int = 1,
    num_segments: int = 4,
    copied_stake: Optional[float] = None,
) -> AdversarialScenario:
    """A lag-`lag` weight copier against an honest shifting consensus.

    Three validators: an honest anchor holding the consensus majority,
    an honest *copied* validator, and a copier reproducing the copied
    validator's rows `lag` epochs late (:class:`~.dsl.CopyWithLag`).
    The copied validator and the copier carry EQUAL stake — any
    dividend gap is pure information lag, not stake weight. The honest
    schedule shifts its one-hot target at `num_segments - 1` random
    epochs (seeded), because a copier only loses when there is
    something to be late about."""
    rng = np.random.default_rng(seed)
    s = (
        float(copied_stake)
        if copied_stake is not None
        else float(rng.uniform(0.15, 0.3))
    )
    anchor = 1.0 - 2.0 * s
    miners = rng.integers(0, num_miners, size=num_segments)
    # Guarantee at least one real shift even if the draw repeats itself.
    for i in range(1, len(miners)):
        if miners[i] == miners[i - 1]:
            miners[i] = (miners[i] + 1) % num_miners
    honest_clauses = [
        at_epochs(
            OneHot((int(m), int(m), int(m))), lo, hi
        )
        for (lo, hi), m in zip(
            _segments(rng, num_epochs, num_segments), miners
        )
    ]
    spec = ScenarioSpec(
        name=f"lag-{lag} weight copier (seed={seed})",
        validators=(
            f"Honest anchor ({anchor:.2f})",
            f"Honest copied ({s:.2f})",
            f"Copier lag-{lag} ({s:.2f})",
        ),
        base_validator=f"Honest copied ({s:.2f})",
        num_miners=num_miners,
        num_epochs=num_epochs,
        stakes=sequence(Stakes((anchor, s, s))),
        weights=sequence(
            *honest_clauses,
            CopyWithLag(dst=2, src=1, lag=lag),
        ),
    )
    return AdversarialScenario(
        scenario=compile_spec(spec),
        spec=spec,
        roles={"anchor": 0, "copied": 1, "copier": 2},
    )


def cartel_scenario(
    seed: int = 0,
    *,
    num_honest: int = 3,
    cartel_size: int = 1,
    cartel_stake_fraction: float = 0.2,
    num_miners: int = 4,
    num_epochs: int = 24,
) -> AdversarialScenario:
    """A collusion cartel self-dealing to its own miner.

    `cartel_size` validators put their entire weight on one cartel
    miner (the last column); honest validators spread seeded-random
    normalized rows over the honest miners only. While the cartel's
    combined stake fraction stays below the consensus majority
    (`kappa`), the stake-weighted median clips the cartel column to
    (at most) the u16 consensus grid floor — the cartel miner's
    per-epoch incentive is bounded by
    :data:`CARTEL_INCENTIVE_FLOOR_PER_EPOCH`, the bound the property
    suite asserts. Push `cartel_stake_fraction` past kappa and the
    same generator produces the majority-capture counterexample
    (~1.0/epoch: the cartel miner takes the whole incentive pool)."""
    rng = np.random.default_rng(seed)
    V = num_honest + cartel_size
    cartel_miner = num_miners - 1
    honest_share = (1.0 - cartel_stake_fraction) / num_honest
    cartel_share = cartel_stake_fraction / cartel_size
    rows = []
    for v in range(num_honest):
        row = rng.random(num_miners - 1) + 0.1
        row = row / row.sum()
        rows.append(tuple(float(x) for x in row) + (0.0,))
    for _ in range(cartel_size):
        rows.append((0.0,) * (num_miners - 1) + (1.0,))
    stakes = (honest_share,) * num_honest + (cartel_share,) * cartel_size
    spec = ScenarioSpec(
        name=(
            f"cartel f={cartel_stake_fraction:.2f} size={cartel_size} "
            f"(seed={seed})"
        ),
        validators=tuple(
            [f"Honest {v} ({honest_share:.2f})" for v in range(num_honest)]
            + [f"Cartel {c} ({cartel_share:.2f})" for c in range(cartel_size)]
        ),
        base_validator=f"Honest 0 ({honest_share:.2f})",
        num_miners=num_miners,
        num_epochs=num_epochs,
        stakes=sequence(Stakes(stakes)),
        weights=sequence(Rows(tuple(rows))),
    )
    return AdversarialScenario(
        scenario=compile_spec(spec),
        spec=spec,
        roles={
            "cartel_validators": tuple(range(num_honest, V)),
            "cartel_miner": cartel_miner,
        },
    )


def stake_churn_scenario(
    seed: int = 0,
    *,
    num_validators: int = 4,
    num_miners: int = 4,
    num_epochs: int = 30,
    shock_epoch: Optional[int] = None,
) -> AdversarialScenario:
    """A stake-churn shock: one validator leaves (stake to zero) and a
    previously-absent one joins at the shock epoch, total stake
    conserved — the join/leave trajectory of the DSL as an adversary
    (churn is how stake-grinding attacks enter). The joiner is the last
    validator; the leaver is seeded-random among the incumbents."""
    rng = np.random.default_rng(seed)
    shock = (
        int(shock_epoch)
        if shock_epoch is not None
        else int(rng.integers(num_epochs // 3, 2 * num_epochs // 3))
    )
    incumbent = rng.random(num_validators - 1) + 0.2
    incumbent = incumbent / incumbent.sum()
    before = tuple(float(x) for x in incumbent) + (0.0,)
    leaver = int(rng.integers(0, num_validators - 1))
    after = list(before)
    after[-1] = after[leaver]  # the joiner inherits the leaver's stake
    after[leaver] = 0.0
    target = tuple(int(m) for m in rng.integers(0, num_miners, num_validators))
    spec = ScenarioSpec(
        name=f"stake churn at e{shock} (seed={seed})",
        validators=tuple(
            f"Vali {v} ({before[v]:.2f}->{after[v]:.2f})"
            for v in range(num_validators)
        ),
        base_validator=f"Vali 0 ({before[0]:.2f}->{after[0]:.2f})",
        num_miners=num_miners,
        num_epochs=num_epochs,
        stakes=sequence(
            Stakes(before),
            at_epochs(Stakes(tuple(after)), shock),
        ),
        weights=sequence(OneHot(target)),
    )
    return AdversarialScenario(
        scenario=compile_spec(spec),
        spec=spec,
        roles={"leaver": leaver, "joiner": num_validators - 1,
               "shock_epoch": shock},
    )


def takeover_scenario(
    seed: int = 0,
    *,
    num_miners: int = 4,
    num_epochs: int = 30,
    takeover_epoch: Optional[int] = None,
    attacker_fraction: float = 0.6,
) -> AdversarialScenario:
    """A validator takeover at epoch k: the attacker runs as a minority
    honest-looking validator, then seizes `attacker_fraction` of the
    subnet stake (:class:`~.dsl.Takeover`) and redirects its weight to
    its own miner. Paired with a bond reset at the takeover epoch (the
    reference's reset machinery exercised from the DSL)."""
    rng = np.random.default_rng(seed)
    k = (
        int(takeover_epoch)
        if takeover_epoch is not None
        else int(rng.integers(num_epochs // 3, 2 * num_epochs // 3))
    )
    honest_miner = int(rng.integers(0, num_miners - 1))
    attacker_miner = num_miners - 1
    spec = ScenarioSpec(
        name=f"takeover at e{k} (seed={seed})",
        validators=("Honest 0 (0.45)", "Honest 1 (0.45)", "Attacker (0.10)"),
        base_validator="Honest 0 (0.45)",
        num_miners=num_miners,
        num_epochs=num_epochs,
        stakes=sequence(Stakes((0.45, 0.45, 0.1))),
        weights=sequence(
            OneHot((honest_miner, honest_miner, honest_miner)),
            at_epochs(
                OneHot((honest_miner, honest_miner, attacker_miner)), k
            ),
        ),
        events=(
            Takeover(validator=2, epoch=k, stake_fraction=attacker_fraction),
            BondReset(index=2, epoch=k),
        ),
    )
    return AdversarialScenario(
        scenario=compile_spec(spec),
        spec=spec,
        roles={
            "attacker": 2,
            "attacker_miner": attacker_miner,
            "takeover_epoch": k,
        },
    )


# ------------------------------------------------------- property helpers


def liquid_config(**overrides) -> YumaConfig:
    """The property suite's config: liquid alpha ON (the mechanism the
    copier property quantifies over), reference defaults otherwise."""
    return YumaConfig(
        yuma_params=YumaParams(liquid_alpha=True, **overrides)
    )


def total_dividends(
    scenario: Scenario,
    yuma_version: str,
    config: Optional[YumaConfig] = None,
) -> np.ndarray:
    """`[V]` summed per-epoch dividends for one scenario/version — the
    quantity every dividend property compares."""
    from yuma_simulation_tpu.simulation.engine import simulate

    result = simulate(
        scenario,
        yuma_version,
        config,
        save_bonds=False,
        save_incentives=False,
    )
    return np.asarray(result.dividends).sum(axis=0)


def copier_dividend_gap(
    adversary: AdversarialScenario,
    yuma_version: str,
    config: Optional[YumaConfig] = None,
) -> float:
    """copied_total - copier_total; the copier property is `> 0`."""
    totals = total_dividends(
        adversary.scenario,
        yuma_version,
        config if config is not None else liquid_config(),
    )
    return float(
        totals[adversary.roles["copied"]] - totals[adversary.roles["copier"]]
    )


def cartel_miner_incentive(
    adversary: AdversarialScenario,
    yuma_version: str,
    config: Optional[YumaConfig] = None,
) -> float:
    """Total incentive landing on the cartel's self-dealt miner; the
    sub-majority cartel property is `<= num_epochs *
    CARTEL_INCENTIVE_FLOOR_PER_EPOCH` (the consensus median clips the
    column to at most the u16 grid floor)."""
    from yuma_simulation_tpu.simulation.engine import simulate

    result = simulate(
        adversary.scenario,
        yuma_version,
        config,
        save_bonds=False,
        save_incentives=True,
    )
    incentives = np.asarray(result.incentives)
    return float(incentives[:, adversary.roles["cartel_miner"]].sum())
