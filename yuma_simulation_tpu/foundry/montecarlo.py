"""Monte-Carlo bridge: parameter distributions -> batched suites.

The fleet, serve, and sharded tiers consume *suites* — stacked
`[B, E, V, M]` batches, supervised unit partitions, lease-claimed fleet
grids. This module maps **distributions over DSL/generator parameters**
onto those carriers, replacing "the 14 fixed cases" as the population
the platform exercises:

- :func:`sample_params` draws seeded parameter dicts from declarative
  distributions (:class:`Uniform` / :class:`LogUniform` /
  :class:`IntRange` / :class:`Choice`);
- :func:`montecarlo_suite` feeds each draw (plus a per-draw derived
  seed) to any spec/scenario builder — a DSL `ScenarioSpec` factory or
  an adversarial family — and compiles the resulting population;
- :func:`run_montecarlo` dispatches a suite down the chosen carrier:
  the plain batched engine (`simulate_batch`), the supervised tier
  (`SweepSupervisor.run_batch`), the sharded pod path
  (`simulate_batch_sharded`), or the work-stealing fleet
  (`run_fleet_batch`) — bitwise-identical dividends on every route
  (the carriers' own contracts, exercised over *generated* populations
  by tests/unit/test_foundry_montecarlo.py);
- :func:`montecarlo_config_batch` is the hyperparameter twin: a seeded
  sample over `YumaConfig` float fields as one batched config pytree
  (+ its points list), the exact payload `run_fleet_grid(configs=...,
  points=...)` and `SweepSupervisor.run_grid` share.

Determinism contract: every draw derives from one integer seed via
`np.random.default_rng`; hosts coordinate by exchanging the SEED (and
the distribution spec), never the sample.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

import numpy as np

from yuma_simulation_tpu.foundry.dsl import ScenarioSpec, compile_spec
from yuma_simulation_tpu.scenarios.base import Scenario

# ------------------------------------------------------------ distributions


@dataclass(frozen=True)
class Uniform:
    lo: float
    hi: float

    def sample(self, rng: np.random.Generator):
        return float(rng.uniform(self.lo, self.hi))


@dataclass(frozen=True)
class LogUniform:
    lo: float
    hi: float

    def sample(self, rng: np.random.Generator):
        return float(
            np.exp(rng.uniform(np.log(self.lo), np.log(self.hi)))
        )


@dataclass(frozen=True)
class IntRange:
    lo: int
    hi: int  # inclusive

    def sample(self, rng: np.random.Generator):
        return int(rng.integers(self.lo, self.hi + 1))


@dataclass(frozen=True)
class Choice:
    values: tuple

    def sample(self, rng: np.random.Generator):
        return self.values[int(rng.integers(0, len(self.values)))]


def sample_params(
    distributions: dict, num_samples: int, seed: int
) -> list[dict]:
    """`num_samples` seeded draws from `{param: distribution}` (a plain
    value is treated as a constant). Deterministic in (distributions,
    num_samples, seed); draw i of a longer run equals draw i of a
    shorter one (one child RNG per draw, spawned in order)."""
    out = []
    for i in range(num_samples):
        rng = np.random.default_rng((seed, i))
        point = {}
        for name in sorted(distributions):
            dist = distributions[name]
            point[name] = (
                dist.sample(rng) if hasattr(dist, "sample") else dist
            )
        out.append(point)
    return out


def derived_seed(seed: int, index: int) -> int:
    """The per-draw integer seed handed to scenario builders — stable,
    collision-resistant (SeedSequence-hashed), exchangeable between
    hosts as plain ints."""
    return int(np.random.SeedSequence([seed, index]).generate_state(1)[0])


# ------------------------------------------------------------ suite builders


def montecarlo_specs(
    builder: Callable[..., ScenarioSpec],
    distributions: dict,
    num_samples: int,
    seed: int,
) -> tuple[list[ScenarioSpec], list[dict]]:
    """Sample `builder(seed=<derived>, **params)` spec draws. The
    builder is any callable returning a :class:`ScenarioSpec`."""
    points = sample_params(distributions, num_samples, seed)
    specs = [
        builder(seed=derived_seed(seed, i), **point)
        for i, point in enumerate(points)
    ]
    return specs, points


def montecarlo_suite(
    builder: Callable,
    distributions: dict,
    num_samples: int,
    seed: int,
) -> tuple[list[Scenario], list[dict]]:
    """Sample and MATERIALIZE a scenario population. `builder` may
    return a `ScenarioSpec` (compiled here), a `Scenario`, or an
    :class:`~.adversarial.AdversarialScenario` (unwrapped)."""
    points = sample_params(distributions, num_samples, seed)
    scenarios: list[Scenario] = []
    for i, point in enumerate(points):
        built = builder(seed=derived_seed(seed, i), **point)
        if isinstance(built, ScenarioSpec):
            scenarios.append(compile_spec(built))
        elif isinstance(built, Scenario):
            scenarios.append(built.validate())
        elif hasattr(built, "scenario"):
            scenarios.append(built.scenario)
        else:
            raise TypeError(
                "montecarlo builder must return a ScenarioSpec, "
                f"Scenario, or AdversarialScenario, got {type(built)!r}"
            )
    return scenarios, points


def montecarlo_config_batch(
    distributions: dict, num_samples: int, seed: int, **base
):
    """A seeded Monte-Carlo sample over `YumaConfig` FLOAT fields as one
    batched config pytree + its points list — the `run_fleet_grid(
    configs=..., points=...)` / `SweepSupervisor.run_grid` payload
    (config_grid's cartesian twin, with distributions for axes).
    Static fields (`liquid_alpha`, overrides) cannot be sampled — they
    select different compiled programs; set them via `base`
    (`simulation=` / `yuma_params=`)."""
    from yuma_simulation_tpu.simulation.sweep import build_config_batch

    base_simulation = base.pop("simulation", None)
    base_params = base.pop("yuma_params", None)
    if base:
        raise ValueError(f"unknown base config fields: {sorted(base)}")
    points = sample_params(distributions, num_samples, seed)
    # build_config_batch owns the static-field exclusion and the f32
    # leaf stacking — one source of truth with config_grid.
    return build_config_batch(points, base_simulation, base_params), points


# ------------------------------------------------------------------ carriers


def run_montecarlo(
    scenarios: Sequence[Scenario],
    yuma_version: str,
    config=None,
    *,
    route: str = "batch",
    mesh=None,
    fleet=None,
    supervisor=None,
    pack: bool = False,
) -> dict:
    """Dispatch a generated suite down one platform carrier.

    `route`:
      - ``"batch"`` — one batched engine dispatch (`simulate_batch`;
        same-shaped suites stack, heterogeneous suites donor-pack);
      - ``"supervised"`` — the full single-host resilience tier
        (:meth:`..resilience.supervisor.SweepSupervisor.run_batch`);
      - ``"sharded"`` — the pod path
        (:func:`..parallel.sharded.simulate_batch_sharded`; needs
        `mesh`);
      - ``"fleet"`` — this process's share of a work-stealing fleet
        (:func:`..fabric.scheduler.run_fleet_batch`; needs `fleet`, a
        store dir or FleetConfig).

    Returns the carrier's own dict with `"dividends"` always present.
    Bitwise contract: per-lane dividends are identical on every route
    (each carrier's existing bitwise guarantee, now quantified over
    generated populations)."""
    scenarios = list(scenarios)
    if route == "batch":
        from yuma_simulation_tpu.models.config import YumaConfig
        from yuma_simulation_tpu.models.variants import variant_for_version
        from yuma_simulation_tpu.simulation.sweep import (
            pack_scenarios,
            simulate_batch,
            stack_scenarios,
        )

        config = config if config is not None else YumaConfig()
        spec = variant_for_version(yuma_version)
        same_shape = len({s.weights.shape for s in scenarios}) == 1
        if same_shape and not pack:
            W, S, ri, re = stack_scenarios(scenarios)
            ys = simulate_batch(W, S, ri, re, config, spec)
        else:
            W, S, ri, re, mask = pack_scenarios(scenarios)
            ys = simulate_batch(
                W, S, ri, re, config, spec, miner_mask=mask
            )
        return {"dividends": np.asarray(ys["dividends"])}
    if route == "supervised":
        from yuma_simulation_tpu.resilience.supervisor import SweepSupervisor

        sup = supervisor if supervisor is not None else SweepSupervisor(
            directory=None
        )
        return sup.run_batch(scenarios, yuma_version, config, pack=pack)
    if route == "sharded":
        if mesh is None:
            raise ValueError("route='sharded' needs mesh=")
        from yuma_simulation_tpu.parallel.sharded import (
            simulate_batch_sharded,
        )

        return simulate_batch_sharded(
            scenarios, yuma_version, config, mesh=mesh
        )
    if route == "fleet":
        if fleet is None:
            raise ValueError("route='fleet' needs fleet= (a store dir)")
        from yuma_simulation_tpu.fabric.scheduler import run_fleet_batch

        return run_fleet_batch(
            scenarios, yuma_version, fleet, config=config,
            supervisor=supervisor,
        )
    raise ValueError(
        f"unknown route {route!r} "
        "(want batch | supervised | sharded | fleet)"
    )
