"""The scenario foundry: generated workloads for every platform tier.

Three pillars, all compiling down to the ONE dense `Scenario`
representation (`weights[E, V, M]` / `stakes[E, V]`) the planner,
donor packing, numerics capture, and every engine rung already consume:

- :mod:`.dsl` — the declarative scenario DSL: frozen, serializable
  primitives (stake trajectories, weight schedules, epoch events)
  combined by `sequence`/`overlay`/`at_epochs` into a `ScenarioSpec`,
  compiled deterministically by `compile_spec` (built-in cases
  re-expressed in it compile BITWISE equal to the hand-built arrays);
- :mod:`.metagraph` — Bittensor metagraph snapshot ingestion (documented
  JSON/npz schema, deterministic synthetic snapshots at real-subnet
  shape V=256 x M=4096) so real subnets run through every Yuma variant;
- :mod:`.adversarial` — weight-copying, collusion cartels, stake-churn
  shocks and validator takeover as parameterized generated families,
  each paired with property assertions on dividend outcomes;
- :mod:`.montecarlo` — distributions over DSL/generator parameters as
  batched suites feeding `simulate_batch`, `SweepSupervisor`,
  `simulate_batch_sharded`, and the fleet drivers.

``python -m yuma_simulation_tpu.foundry --drill --bundle-dir DIR`` runs
a seeded generated-suite supervisor drill into a flight bundle (the CI
scenario lane, gated by ``obsreport --check`` + ``driftreport --check``).
"""

from yuma_simulation_tpu.foundry.adversarial import (  # noqa: F401
    CARTEL_INCENTIVE_FLOOR_PER_EPOCH,
    LIQUID_ALPHA_VERSIONS,
    AdversarialScenario,
    cartel_miner_incentive,
    cartel_scenario,
    copier_dividend_gap,
    liquid_config,
    stake_churn_scenario,
    takeover_scenario,
    total_dividends,
    weight_copier_scenario,
)
from yuma_simulation_tpu.foundry.dsl import (  # noqa: F401
    BondReset,
    Clause,
    CopyWithLag,
    NoisyConsensusFollower,
    OneHot,
    Rows,
    ScenarioSpec,
    SpecError,
    StakeDrift,
    Stakes,
    Takeover,
    at_epochs,
    builtin_case_specs,
    compile_spec,
    overlay,
    sequence,
    spec_from_dict,
    spec_from_json,
    spec_key,
    spec_to_dict,
    spec_to_json,
)
from yuma_simulation_tpu.foundry.metagraph import (  # noqa: F401
    MetagraphSnapshot,
    SnapshotError,
    load_metagraph_snapshot,
    save_metagraph_snapshot,
    scenario_from_snapshot,
    synthetic_snapshot,
)
from yuma_simulation_tpu.foundry.montecarlo import (  # noqa: F401
    Choice,
    IntRange,
    LogUniform,
    Uniform,
    derived_seed,
    montecarlo_config_batch,
    montecarlo_specs,
    montecarlo_suite,
    run_montecarlo,
    sample_params,
)
